"""Embedding-table service — the scoped parameter-server analog.

The reference's defining scale claim ("trillions of parameters") rests on
its brpc parameter server holding sparse embedding tables in host RAM
across servers, with workers doing pull_sparse/push_sparse around each
step (/root/reference/paddle/fluid/distributed/table/common_sparse_table.h,
/root/reference/paddle/fluid/distributed/service/brpc_ps_client.cc).

TPU-native scoping (SURVEY §7 hard part (f)): the dense model lives on the
device mesh; only the *huge sparse tables* need the PS pattern, and they
sit on the host(s) beside the input pipeline. This module provides:

* :class:`SparseTable` — one host-RAM table shard: hash-map vocab id →
  row vector, created on first touch (the reference's auto-growth
  semantics), with per-row optimizer slots (sgd / adagrad / adam —
  the reference table's "optimizer in the table" design).
* :class:`EmbeddingService` — shards rows over N tables by ``id % N``
  (the reference's shard_num routing, brpc_ps_client.cc SparseTable
  partition); pull/push are the client API.
* :class:`DistributedEmbedding` — an ``nn.Layer`` that pulls rows on the
  host path, feeds them to the device as a dense leaf, and pushes the
  row gradient back on backward (a tape hook — the async push_sparse
  analog), then lets the table apply its own update.

Peak device/grad memory is O(batch ids × dim) — independent of the table's
vocabulary, which may exceed host RAM × shards only bounded by disk.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

__all__ = ["SparseTable", "DenseTable", "EmbeddingService",
           "DistributedEmbedding"]


class SparseTable:
    """One table shard: id → (row, slots). Thread-safe; rows materialize on
    first pull (reference common_sparse_table.h Init on pull)."""

    def __init__(self, dim: int, initializer: Optional[Callable] = None,
                 optimizer: str = "sgd", lr: float = 0.01,
                 adagrad_eps: float = 1e-6, beta1: float = 0.9,
                 beta2: float = 0.999, adam_eps: float = 1e-8,
                 seed: int = 0):
        self.dim = int(dim)
        self.lr = float(lr)
        self.optimizer = optimizer
        if optimizer not in ("sgd", "adagrad", "adam"):
            raise ValueError(f"unknown table optimizer {optimizer!r}")
        self._adagrad_eps = adagrad_eps
        self._beta1, self._beta2, self._adam_eps = beta1, beta2, adam_eps
        self._rows: Dict[int, np.ndarray] = {}
        self._slots: Dict[int, List[np.ndarray]] = {}
        self._steps: Dict[int, int] = {}
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()
        self._init = initializer or (
            lambda rng, dim: (rng.standard_normal(dim) * 0.01)
            .astype(np.float32))

    def __len__(self) -> int:
        return len(self._rows)

    def _ensure(self, i: int) -> np.ndarray:
        row = self._rows.get(i)
        if row is None:
            row = self._init(self._rng, self.dim)
            self._rows[i] = row
            if self.optimizer == "adagrad":
                self._slots[i] = [np.zeros(self.dim, np.float32)]
            elif self.optimizer == "adam":
                self._slots[i] = [np.zeros(self.dim, np.float32),
                                  np.zeros(self.dim, np.float32)]
                self._steps[i] = 0
        return row

    def pull(self, ids: Sequence[int]) -> np.ndarray:
        """[n, dim] rows, creating missing ones (pull_sparse)."""
        with self._lock:
            return np.stack([self._ensure(int(i)) for i in ids]) \
                if len(ids) else np.zeros((0, self.dim), np.float32)

    def push(self, ids: Sequence[int], grads: np.ndarray) -> None:
        """Apply the table's optimizer per row (push_sparse + in-table
        update). ``grads``: [n, dim]; duplicate ids accumulate."""
        grads = np.asarray(grads, np.float32)
        with self._lock:
            for k, i in enumerate(ids):
                i = int(i)
                row = self._ensure(i)
                g = grads[k]
                if self.optimizer == "sgd":
                    row -= self.lr * g
                elif self.optimizer == "adagrad":
                    acc = self._slots[i][0]
                    acc += g * g
                    row -= self.lr * g / (np.sqrt(acc) + self._adagrad_eps)
                else:  # adam
                    m1, m2 = self._slots[i]
                    self._steps[i] += 1
                    t = self._steps[i]
                    m1 *= self._beta1
                    m1 += (1 - self._beta1) * g
                    m2 *= self._beta2
                    m2 += (1 - self._beta2) * g * g
                    bc1 = 1 - self._beta1 ** t
                    bc2 = 1 - self._beta2 ** t
                    row -= self.lr * (m1 / bc1) / (
                        np.sqrt(m2 / bc2) + self._adam_eps)

    def state_dict(self) -> dict:
        with self._lock:
            return {"dim": self.dim, "optimizer": self.optimizer,
                    "lr": self.lr,
                    "rows": {i: r.copy() for i, r in self._rows.items()},
                    "slots": {i: [s.copy() for s in ss]
                              for i, ss in self._slots.items()},
                    "steps": dict(self._steps)}

    def load_state_dict(self, state: dict) -> None:
        with self._lock:
            self._rows = {int(i): np.asarray(r, np.float32)
                          for i, r in state["rows"].items()}
            self._slots = {int(i): [np.asarray(s, np.float32) for s in ss]
                           for i, ss in state["slots"].items()}
            self._steps = {int(i): int(t)
                           for i, t in state.get("steps", {}).items()}


class DenseTable:
    """One dense parameter block living on the PS, updated by worker
    gradients through the table's own optimizer — the analog of the
    reference's CommonDenseTable (/root/reference/paddle/fluid/
    distributed/table/common_dense_table.h): dense params trained
    asynchronously through the PS rather than held worker-local.

    Two update surfaces (both in the remote ``RPC_METHODS`` whitelist so
    a :class:`~paddle1_tpu.distributed.ps_server.RemoteTable` reaches
    them over the wire):

    * ``push_dense_grad(grad)`` — in-table sgd/adagrad/adam step
      (async-SGD mode; the reference Communicator's send path).
    * ``push_dense_delta(delta)`` — additive merge of a worker-side
      parameter delta (geo-async SGD; the reference's GeoSgd/
      sparse_geo_table delta semantics applied to the dense block).

    ``version`` counts applied updates — the staleness bookkeeping the
    geo mode's bounded-staleness contract is tested against.
    """

    RPC_METHODS = frozenset({"pull_dense", "push_dense_grad",
                             "push_dense_delta", "set_value",
                             "get_version", "bump_version"})

    def __init__(self, shape, initializer: Optional[Callable] = None,
                 optimizer: str = "sgd", lr: float = 0.01,
                 adagrad_eps: float = 1e-6, beta1: float = 0.9,
                 beta2: float = 0.999, adam_eps: float = 1e-8,
                 seed: int = 0):
        self.shape = tuple(int(s) for s in shape)
        self.lr = float(lr)
        self.optimizer = optimizer
        if optimizer not in ("sgd", "adagrad", "adam"):
            raise ValueError(f"unknown table optimizer {optimizer!r}")
        self._adagrad_eps = adagrad_eps
        self._beta1, self._beta2, self._adam_eps = beta1, beta2, adam_eps
        rng = np.random.default_rng(seed)
        init = initializer or (
            lambda r, shp: (r.standard_normal(shp) * 0.01)
            .astype(np.float32))
        self._value = np.asarray(init(rng, self.shape), np.float32)
        self._m1 = np.zeros(self.shape, np.float32)
        self._m2 = np.zeros(self.shape, np.float32)
        self._step = 0
        self.version = 0
        self._lock = threading.Lock()

    # dim handshake: RemoteTable.__init__ reads it; a dense block
    # reports its trailing dim (EmbeddingService never hosts these)
    @property
    def dim(self) -> int:
        return self.shape[-1] if self.shape else 1

    def __len__(self) -> int:
        return self.shape[0] if self.shape else 1

    def pull_dense(self) -> np.ndarray:
        with self._lock:
            return self._value.copy()

    def get_version(self) -> int:
        with self._lock:
            return self.version

    def bump_version(self) -> None:
        """Advance the version WITHOUT applying an update. A sync-mode
        trainer whose param had no grad this round (frozen/unused)
        posts this instead of a push, so every table's version still
        advances by exactly ``trainers`` per round — peers' barriers
        stay satisfiable instead of stalling to their timeout."""
        with self._lock:
            self.version += 1

    def set_value(self, value) -> None:
        value = np.asarray(value, np.float32)
        if value.shape != self.shape:
            raise ValueError(f"set_value shape {value.shape} != table "
                             f"shape {self.shape}")
        with self._lock:
            self._value = value.copy()
            self.version += 1

    def push_dense_grad(self, grad) -> None:
        g = np.asarray(grad, np.float32)
        if g.shape != self.shape:
            raise ValueError(f"grad shape {g.shape} != table shape "
                             f"{self.shape}")
        with self._lock:
            v = self._value
            if self.optimizer == "sgd":
                v -= self.lr * g
            elif self.optimizer == "adagrad":
                self._m1 += g * g
                v -= self.lr * g / (np.sqrt(self._m1) + self._adagrad_eps)
            else:  # adam
                self._step += 1
                self._m1 *= self._beta1
                self._m1 += (1 - self._beta1) * g
                self._m2 *= self._beta2
                self._m2 += (1 - self._beta2) * g * g
                bc1 = 1 - self._beta1 ** self._step
                bc2 = 1 - self._beta2 ** self._step
                v -= self.lr * (self._m1 / bc1) / (
                    np.sqrt(self._m2 / bc2) + self._adam_eps)
            self.version += 1

    def push_dense_delta(self, delta) -> None:
        d = np.asarray(delta, np.float32)
        if d.shape != self.shape:
            raise ValueError(f"delta shape {d.shape} != table shape "
                             f"{self.shape}")
        with self._lock:
            self._value += d
            self.version += 1

    def state_dict(self) -> dict:
        with self._lock:
            return {"shape": self.shape, "optimizer": self.optimizer,
                    "lr": self.lr, "value": self._value.copy(),
                    "m1": self._m1.copy(), "m2": self._m2.copy(),
                    "step": self._step, "version": self.version}

    def load_state_dict(self, state: dict) -> None:
        sshape = tuple(state.get("shape", np.shape(state["value"])))
        if sshape != self.shape:
            raise ValueError(
                f"DenseTable checkpoint has shape {sshape}, this table "
                f"is {self.shape}")
        sopt = state.get("optimizer", self.optimizer)
        if sopt != self.optimizer:
            raise ValueError(
                f"DenseTable checkpoint was trained with optimizer "
                f"{sopt!r}, this table is configured {self.optimizer!r} "
                "— the slot values would be misinterpreted")
        with self._lock:
            self._value = np.asarray(state["value"], np.float32)
            self._m1 = np.asarray(state["m1"], np.float32)
            self._m2 = np.asarray(state["m2"], np.float32)
            self._step = int(state.get("step", 0))
            self.version = int(state.get("version", 0))
            self.lr = float(state.get("lr", self.lr))


class EmbeddingService:
    """Shards ids over ``num_shards`` tables by ``id % num_shards`` (the
    reference's table-partition routing). In a multi-host deployment each
    shard lives on one host; here shards are in-process with independent
    locks, preserving the interface and the concurrency structure."""

    def __init__(self, dim: int, num_shards: int = 1, shards=None,
                 **table_kwargs):
        self.dim = int(dim)
        if shards is not None:
            # prebuilt shards (e.g. ps_server.RemoteTable clients) — any
            # object with the SparseTable pull/push/state interface
            self.shards = list(shards)
            if not self.shards:
                raise ValueError("shards must be non-empty")
            for k, sh in enumerate(self.shards):
                sdim = getattr(sh, "dim", None)
                if sdim is not None and int(sdim) != self.dim:
                    raise ValueError(
                        f"shard {k} serves dim={sdim} but the service was "
                        f"configured with dim={self.dim} — the trainer and "
                        f"table servers disagree on the embedding width")
            self.num_shards = len(self.shards)
            return
        if num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        self.num_shards = int(num_shards)
        self.shards = [SparseTable(dim, seed=s, **table_kwargs)
                       for s in range(num_shards)]

    def __len__(self) -> int:
        return sum(len(s) for s in self.shards)

    def _route(self, ids: np.ndarray):
        shard_idx = ids % self.num_shards
        return [(s, np.nonzero(shard_idx == s)[0])
                for s in range(self.num_shards)]

    def pull(self, ids: Sequence[int]) -> np.ndarray:
        ids = np.asarray(ids, np.int64).reshape(-1)
        out = np.empty((ids.shape[0], self.dim), np.float32)
        for s, pos in self._route(ids):
            if pos.size:
                out[pos] = self.shards[s].pull(ids[pos])
        return out

    def push(self, ids: Sequence[int], grads: np.ndarray) -> None:
        ids = np.asarray(ids, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32)
        for s, pos in self._route(ids):
            if pos.size:
                self.shards[s].push(ids[pos], grads[pos])

    def state_dict(self) -> dict:
        return {"dim": self.dim, "num_shards": self.num_shards,
                "shards": [s.state_dict() for s in self.shards]}

    def load_state_dict(self, state: dict) -> None:
        for shard, sd in zip(self.shards, state["shards"]):
            shard.load_state_dict(sd)


class DistributedEmbedding:
    """Layer over :class:`EmbeddingService`: host pull → device compute →
    grad push on backward (reference distributed lookup_table /
    fleet.embedding semantics).

    Forward contracts the batch to its *unique* ids, pulls those rows once,
    and gathers on device — so both transfer and gradient are O(unique ids
    × dim). The pulled block is a differentiable leaf whose gradient hook
    pushes to the service and triggers the in-table update; no dense
    [vocab, dim] tensor ever exists on either side.
    """

    def __init__(self, service: EmbeddingService):
        self.service = service

    def __call__(self, ids):
        import jax.numpy as jnp
        from ..core.tensor import Tensor
        from ..nn import functional as F  # noqa: F401 (tape ops)
        from ..autograd.engine import apply

        ids_np = np.asarray(ids.numpy() if hasattr(ids, "numpy") else ids,
                            np.int64)
        uniq, inv = np.unique(ids_np.reshape(-1), return_inverse=True)
        block = self.service.pull(uniq)                      # [u, dim]
        pulled = Tensor(jnp.asarray(block), stop_gradient=False)

        def on_grad(g):
            self.service.push(uniq, np.asarray(g.data))
            return None

        pulled.register_hook(on_grad)
        inv_j = jnp.asarray(inv.reshape(ids_np.shape), jnp.int32)
        out = apply("dist_embedding_gather",
                    lambda w: jnp.take(w, inv_j, axis=0), (pulled,))
        self._last_pulled = pulled
        return out
