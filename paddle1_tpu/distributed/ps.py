"""Embedding-table service — the scoped parameter-server analog.

The reference's defining scale claim ("trillions of parameters") rests on
its brpc parameter server holding sparse embedding tables in host RAM
across servers, with workers doing pull_sparse/push_sparse around each
step (/root/reference/paddle/fluid/distributed/table/common_sparse_table.h,
/root/reference/paddle/fluid/distributed/service/brpc_ps_client.cc).

TPU-native scoping (SURVEY §7 hard part (f)): the dense model lives on the
device mesh; only the *huge sparse tables* need the PS pattern, and they
sit on the host(s) beside the input pipeline. This module provides:

* :class:`SparseTable` — one host-RAM table shard: hash-map vocab id →
  row vector, created on first touch (the reference's auto-growth
  semantics), with per-row optimizer slots (sgd / adagrad / adam —
  the reference table's "optimizer in the table" design).
* :class:`EmbeddingService` — shards rows over N tables by ``id % N``
  (the reference's shard_num routing, brpc_ps_client.cc SparseTable
  partition); pull/push are the client API.
* :class:`DistributedEmbedding` — an ``nn.Layer`` that pulls rows on the
  host path, feeds them to the device as a dense leaf, and pushes the
  row gradient back on backward (a tape hook — the async push_sparse
  analog), then lets the table apply its own update.

Peak device/grad memory is O(batch ids × dim) — independent of the table's
vocabulary, which may exceed host RAM × shards only bounded by disk.
"""

from __future__ import annotations

import threading
import weakref
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..core import collective_sanitizer as _csan

__all__ = ["SparseTable", "DenseTable", "EmbeddingService",
           "DistributedEmbedding", "pack_table_state",
           "unpack_table_state"]

# Live DistributedEmbedding instances whose pending gradients flush
# when a full backward pass ends. One engine-level callback (registered
# lazily, deduped by identity) walks a WeakSet, so instances stay
# collectable and the callback list never grows per layer.
_live_embeddings: "weakref.WeakSet" = weakref.WeakSet()


def _flush_live_embeddings() -> None:
    for emb in list(_live_embeddings):
        emb.flush_grads()


def _track_for_backward_flush(emb: "DistributedEmbedding") -> None:
    from ..autograd.engine import register_backward_end_callback
    _live_embeddings.add(emb)
    register_backward_end_callback(_flush_live_embeddings)


def _coalesce(ids: np.ndarray, grads: np.ndarray):
    """Sum duplicate-id gradients so the table applies ONE optimizer
    step per unique id — the dense-equivalent semantics (a dense
    embedding's scatter-add produces a single summed row gradient; the
    reference merges SelectedRows the same way before push_sparse).
    Without this, adam/adagrad would take one slot update per
    *occurrence* and diverge from the dense optimizer at 1e-1 scale."""
    ids = np.asarray(ids, np.int64).reshape(-1)
    grads = np.asarray(grads, np.float32).reshape(ids.shape[0], -1)
    uniq, inv = np.unique(ids, return_inverse=True)
    if uniq.shape[0] == ids.shape[0]:
        return ids, grads
    summed = np.zeros((uniq.shape[0], grads.shape[1]), np.float32)
    np.add.at(summed, inv, grads)
    return uniq, summed


class SparseTable:
    """One table shard: id → (row, slots). Thread-safe; rows materialize on
    first pull (reference common_sparse_table.h Init on pull).

    ``evict``/``admit`` move rows *with their optimizer slots and adam
    step counts* between tiers (HBM ↔ host ↔ remote) — the heter_ps
    demote/promote contract: a row that leaves and comes back resumes
    its bias-correction schedule exactly where it stopped."""

    RPC_METHODS = frozenset({"evict", "admit", "has"})

    def __init__(self, dim: int, initializer: Optional[Callable] = None,
                 optimizer: str = "sgd", lr: float = 0.01,
                 adagrad_eps: float = 1e-6, beta1: float = 0.9,
                 beta2: float = 0.999, adam_eps: float = 1e-8,
                 seed: int = 0):
        self.dim = int(dim)
        self.lr = float(lr)
        self.optimizer = optimizer
        if optimizer not in ("sgd", "adagrad", "adam"):
            raise ValueError(f"unknown table optimizer {optimizer!r}")
        self._adagrad_eps = adagrad_eps
        self._beta1, self._beta2, self._adam_eps = beta1, beta2, adam_eps
        self._rows: Dict[int, np.ndarray] = {}
        self._slots: Dict[int, List[np.ndarray]] = {}
        self._steps: Dict[int, int] = {}
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()
        self._init = initializer or (
            lambda rng, dim: (rng.standard_normal(dim) * 0.01)
            .astype(np.float32))

    def __len__(self) -> int:
        return len(self._rows)

    def _ensure(self, i: int) -> np.ndarray:
        row = self._rows.get(i)
        if row is None:
            row = self._init(self._rng, self.dim)
            self._rows[i] = row
            if self.optimizer == "adagrad":
                self._slots[i] = [np.zeros(self.dim, np.float32)]
            elif self.optimizer == "adam":
                self._slots[i] = [np.zeros(self.dim, np.float32),
                                  np.zeros(self.dim, np.float32)]
                self._steps[i] = 0
        return row

    def pull(self, ids: Sequence[int]) -> np.ndarray:
        """[n, dim] rows, creating missing ones (pull_sparse)."""
        with self._lock:
            return np.stack([self._ensure(int(i)) for i in ids]) \
                if len(ids) else np.zeros((0, self.dim), np.float32)

    def push(self, ids: Sequence[int], grads: np.ndarray) -> None:
        """Apply the table's optimizer per row (push_sparse + in-table
        update). ``grads``: [n, dim]; duplicate ids are coalesced to a
        single summed-gradient optimizer step per unique id (the dense
        scatter-add equivalence — see :func:`_coalesce`)."""
        ids, grads = _coalesce(ids, grads)
        with self._lock:
            for k, i in enumerate(ids):
                i = int(i)
                row = self._ensure(i)
                g = grads[k]
                if self.optimizer == "sgd":
                    row -= self.lr * g
                elif self.optimizer == "adagrad":
                    acc = self._slots[i][0]
                    acc += g * g
                    row -= self.lr * g / (np.sqrt(acc) + self._adagrad_eps)
                else:  # adam
                    m1, m2 = self._slots[i]
                    self._steps[i] += 1
                    t = self._steps[i]
                    m1 *= self._beta1
                    m1 += (1 - self._beta1) * g
                    m2 *= self._beta2
                    m2 += (1 - self._beta2) * g * g
                    bc1 = 1 - self._beta1 ** t
                    bc2 = 1 - self._beta2 ** t
                    row -= self.lr * (m1 / bc1) / (
                        np.sqrt(m2 / bc2) + self._adam_eps)

    # -- tier-bridge surface (heter_ps demote/promote) ----------------------

    @property
    def n_slots(self) -> int:
        return {"sgd": 0, "adagrad": 1, "adam": 2}[self.optimizer]

    def has(self, ids: Sequence[int]) -> np.ndarray:
        """bool [n]: which ids are materialized (no side effects)."""
        with self._lock:
            return np.array([int(i) in self._rows for i in ids], bool)

    def evict(self, ids: Sequence[int], create: bool = False) -> dict:
        """Remove rows and hand them (plus slots/steps) to the caller —
        the move half of a tier transfer. ``create=True`` materializes
        missing ids first (promotion of never-seen ids inherits the
        table's first-touch init), else missing ids are skipped.
        Returns arrays: ids [n], rows [n, dim], slots [n, n_slots, dim],
        steps [n]."""
        req = np.asarray(ids, np.int64).reshape(-1)
        out_ids, rows, slots, steps = [], [], [], []
        with self._lock:
            for i in req:
                i = int(i)
                if i not in self._rows:
                    if not create:
                        continue
                    self._ensure(i)
                out_ids.append(i)
                rows.append(self._rows.pop(i))
                ss = self._slots.pop(i, [])
                slots.append(np.stack(ss) if ss else
                             np.zeros((0, self.dim), np.float32))
                steps.append(self._steps.pop(i, 0))
        n = len(out_ids)
        return {"ids": np.asarray(out_ids, np.int64),
                "rows": (np.stack(rows) if n
                         else np.zeros((0, self.dim), np.float32)),
                "slots": (np.stack(slots) if n
                          else np.zeros((0, self.n_slots, self.dim),
                                        np.float32)),
                "steps": np.asarray(steps, np.int64)}

    def admit(self, ids: Sequence[int], rows, slots=None,
              steps=None) -> None:
        """Install rows (the other half of a tier transfer), overwriting
        any resident value. ``slots``/``steps`` restore optimizer state;
        absent slots re-init to zero (a fresh row)."""
        req = np.asarray(ids, np.int64).reshape(-1)
        rows = np.asarray(rows, np.float32).reshape(req.shape[0],
                                                    self.dim)
        slots = None if slots is None else np.asarray(slots, np.float32)
        steps = None if steps is None else \
            np.asarray(steps, np.int64).reshape(-1)
        with self._lock:
            for k, i in enumerate(req):
                i = int(i)
                self._rows[i] = rows[k].copy()
                if self.n_slots:
                    if slots is not None and slots.shape[1] == \
                            self.n_slots:
                        self._slots[i] = [slots[k, j].copy()
                                          for j in range(self.n_slots)]
                    else:
                        self._slots[i] = [np.zeros(self.dim, np.float32)
                                          for _ in range(self.n_slots)]
                if self.optimizer == "adam":
                    self._steps[i] = int(steps[k]) if steps is not None \
                        else 0

    def state_dict(self) -> dict:
        with self._lock:
            return {"dim": self.dim, "optimizer": self.optimizer,
                    "lr": self.lr,
                    "rows": {i: r.copy() for i, r in self._rows.items()},
                    "slots": {i: [s.copy() for s in ss]
                              for i, ss in self._slots.items()},
                    "steps": dict(self._steps)}

    def load_state_dict(self, state: dict) -> None:
        with self._lock:
            self._rows = {int(i): np.asarray(r, np.float32)
                          for i, r in state["rows"].items()}
            self._slots = {int(i): [np.asarray(s, np.float32) for s in ss]
                           for i, ss in state["slots"].items()}
            self._steps = {int(i): int(t)
                           for i, t in state.get("steps", {}).items()}


def pack_table_state(state: dict) -> dict:
    """Flatten a :meth:`SparseTable.state_dict` mapping (int-keyed row /
    slot / step dicts) into a dict of plain ndarrays so it can ride an
    npz checkpoint sidecar. Inverse of :func:`unpack_table_state`."""
    dim = int(state["dim"])
    ids = sorted(int(i) for i in state["rows"])
    nslots = (len(next(iter(state["slots"].values())))
              if state.get("slots") else 0)
    if ids:
        rows = np.stack([np.asarray(state["rows"][i], np.float32)
                         for i in ids])
        if nslots:
            slots = np.asarray(
                [[state["slots"][i][k] for k in range(nslots)]
                 for i in ids], np.float32)
        else:
            slots = np.zeros((len(ids), 0, dim), np.float32)
    else:
        rows = np.zeros((0, dim), np.float32)
        slots = np.zeros((0, nslots, dim), np.float32)
    steps = np.asarray([int(state.get("steps", {}).get(i, 0))
                        for i in ids], np.int64)
    return {"ids": np.asarray(ids, np.int64), "rows": rows,
            "slots": slots, "steps": steps,
            "dim": np.asarray(dim, np.int64),
            "lr": np.asarray(float(state["lr"]), np.float64),
            "optimizer": np.asarray(str(state["optimizer"]))}


def unpack_table_state(arrays: dict) -> dict:
    """Rebuild the :meth:`SparseTable.state_dict` mapping from arrays
    produced by :func:`pack_table_state` (e.g. read back out of a
    checkpoint sidecar)."""
    ids = np.asarray(arrays["ids"], np.int64)
    rows = np.asarray(arrays["rows"], np.float32)
    slots = np.asarray(arrays["slots"], np.float32)
    steps = np.asarray(arrays["steps"], np.int64)
    nslots = int(slots.shape[1]) if slots.ndim == 3 else 0
    return {
        "dim": int(arrays["dim"]),
        "optimizer": str(arrays["optimizer"]),
        "lr": float(arrays["lr"]),
        "rows": {int(i): rows[k].copy() for k, i in enumerate(ids)},
        "slots": {int(i): [slots[k, j].copy() for j in range(nslots)]
                  for k, i in enumerate(ids)},
        "steps": {int(i): int(steps[k]) for k, i in enumerate(ids)},
    }


class DenseTable:
    """One dense parameter block living on the PS, updated by worker
    gradients through the table's own optimizer — the analog of the
    reference's CommonDenseTable (/root/reference/paddle/fluid/
    distributed/table/common_dense_table.h): dense params trained
    asynchronously through the PS rather than held worker-local.

    Two update surfaces (both in the remote ``RPC_METHODS`` whitelist so
    a :class:`~paddle1_tpu.distributed.ps_server.RemoteTable` reaches
    them over the wire):

    * ``push_dense_grad(grad)`` — in-table sgd/adagrad/adam step
      (async-SGD mode; the reference Communicator's send path).
    * ``push_dense_delta(delta)`` — additive merge of a worker-side
      parameter delta (geo-async SGD; the reference's GeoSgd/
      sparse_geo_table delta semantics applied to the dense block).

    ``version`` counts applied updates — the staleness bookkeeping the
    geo mode's bounded-staleness contract is tested against.
    """

    RPC_METHODS = frozenset({"pull_dense", "push_dense_grad",
                             "push_dense_delta", "set_value",
                             "get_version", "bump_version"})

    def __init__(self, shape, initializer: Optional[Callable] = None,
                 optimizer: str = "sgd", lr: float = 0.01,
                 adagrad_eps: float = 1e-6, beta1: float = 0.9,
                 beta2: float = 0.999, adam_eps: float = 1e-8,
                 seed: int = 0):
        self.shape = tuple(int(s) for s in shape)
        self.lr = float(lr)
        self.optimizer = optimizer
        if optimizer not in ("sgd", "adagrad", "adam"):
            raise ValueError(f"unknown table optimizer {optimizer!r}")
        self._adagrad_eps = adagrad_eps
        self._beta1, self._beta2, self._adam_eps = beta1, beta2, adam_eps
        rng = np.random.default_rng(seed)
        init = initializer or (
            lambda r, shp: (r.standard_normal(shp) * 0.01)
            .astype(np.float32))
        self._value = np.asarray(init(rng, self.shape), np.float32)
        self._m1 = np.zeros(self.shape, np.float32)
        self._m2 = np.zeros(self.shape, np.float32)
        self._step = 0
        self.version = 0
        self._lock = threading.Lock()

    # dim handshake: RemoteTable.__init__ reads it; a dense block
    # reports its trailing dim (EmbeddingService never hosts these)
    @property
    def dim(self) -> int:
        return self.shape[-1] if self.shape else 1

    def __len__(self) -> int:
        return self.shape[0] if self.shape else 1

    def pull_dense(self) -> np.ndarray:
        with self._lock:
            return self._value.copy()

    def get_version(self) -> int:
        with self._lock:
            return self.version

    def bump_version(self) -> None:
        """Advance the version WITHOUT applying an update. A sync-mode
        trainer whose param had no grad this round (frozen/unused)
        posts this instead of a push, so every table's version still
        advances by exactly ``trainers`` per round — peers' barriers
        stay satisfiable instead of stalling to their timeout."""
        with self._lock:
            self.version += 1

    def set_value(self, value) -> None:
        value = np.asarray(value, np.float32)
        if value.shape != self.shape:
            raise ValueError(f"set_value shape {value.shape} != table "
                             f"shape {self.shape}")
        with self._lock:
            self._value = value.copy()
            self.version += 1

    def push_dense_grad(self, grad) -> None:
        g = np.asarray(grad, np.float32)
        if g.shape != self.shape:
            raise ValueError(f"grad shape {g.shape} != table shape "
                             f"{self.shape}")
        with self._lock:
            v = self._value
            if self.optimizer == "sgd":
                v -= self.lr * g
            elif self.optimizer == "adagrad":
                self._m1 += g * g
                v -= self.lr * g / (np.sqrt(self._m1) + self._adagrad_eps)
            else:  # adam
                self._step += 1
                self._m1 *= self._beta1
                self._m1 += (1 - self._beta1) * g
                self._m2 *= self._beta2
                self._m2 += (1 - self._beta2) * g * g
                bc1 = 1 - self._beta1 ** self._step
                bc2 = 1 - self._beta2 ** self._step
                v -= self.lr * (self._m1 / bc1) / (
                    np.sqrt(self._m2 / bc2) + self._adam_eps)
            self.version += 1

    def push_dense_delta(self, delta) -> None:
        d = np.asarray(delta, np.float32)
        if d.shape != self.shape:
            raise ValueError(f"delta shape {d.shape} != table shape "
                             f"{self.shape}")
        with self._lock:
            self._value += d
            self.version += 1

    def state_dict(self) -> dict:
        with self._lock:
            return {"shape": self.shape, "optimizer": self.optimizer,
                    "lr": self.lr, "value": self._value.copy(),
                    "m1": self._m1.copy(), "m2": self._m2.copy(),
                    "step": self._step, "version": self.version}

    def load_state_dict(self, state: dict) -> None:
        sshape = tuple(state.get("shape", np.shape(state["value"])))
        if sshape != self.shape:
            raise ValueError(
                f"DenseTable checkpoint has shape {sshape}, this table "
                f"is {self.shape}")
        sopt = state.get("optimizer", self.optimizer)
        if sopt != self.optimizer:
            raise ValueError(
                f"DenseTable checkpoint was trained with optimizer "
                f"{sopt!r}, this table is configured {self.optimizer!r} "
                "— the slot values would be misinterpreted")
        with self._lock:
            self._value = np.asarray(state["value"], np.float32)
            self._m1 = np.asarray(state["m1"], np.float32)
            self._m2 = np.asarray(state["m2"], np.float32)
            self._step = int(state.get("step", 0))
            self.version = int(state.get("version", 0))
            self.lr = float(state.get("lr", self.lr))


class EmbeddingService:
    """Shards ids over ``num_shards`` tables by ``id % num_shards`` (the
    reference's table-partition routing). In a multi-host deployment each
    shard lives on one host; here shards are in-process with independent
    locks, preserving the interface and the concurrency structure."""

    def __init__(self, dim: int, num_shards: int = 1, shards=None,
                 **table_kwargs):
        self.dim = int(dim)
        if shards is not None:
            # prebuilt shards (e.g. ps_server.RemoteTable clients) — any
            # object with the SparseTable pull/push/state interface
            self.shards = list(shards)
            if not self.shards:
                raise ValueError("shards must be non-empty")
            for k, sh in enumerate(self.shards):
                sdim = getattr(sh, "dim", None)
                if sdim is not None and int(sdim) != self.dim:
                    raise ValueError(
                        f"shard {k} serves dim={sdim} but the service was "
                        f"configured with dim={self.dim} — the trainer and "
                        f"table servers disagree on the embedding width")
            self.num_shards = len(self.shards)
            return
        if num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        self.num_shards = int(num_shards)
        self.shards = [SparseTable(dim, seed=s, **table_kwargs)
                       for s in range(num_shards)]

    def __len__(self) -> int:
        return sum(len(s) for s in self.shards)

    def _route(self, ids: np.ndarray):
        shard_idx = ids % self.num_shards
        return [(s, np.nonzero(shard_idx == s)[0])
                for s in range(self.num_shards)]

    def pull(self, ids: Sequence[int]) -> np.ndarray:
        ids = np.asarray(ids, np.int64).reshape(-1)
        # the sparse schedule point the PR 14 sanitizer journals: a
        # worker whose pull order/shape diverges from its peers fails
        # typed at verify instead of hanging a collective later
        _csan.note_collective("ps_pull_sparse", (ids,),
                              site="EmbeddingService.pull")
        out = np.empty((ids.shape[0], self.dim), np.float32)
        for s, pos in self._route(ids):
            if pos.size:
                out[pos] = self.shards[s].pull(ids[pos])
        return out

    def push(self, ids: Sequence[int], grads: np.ndarray) -> None:
        ids = np.asarray(ids, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32)
        _csan.note_collective("ps_push_sparse", (ids, grads),
                              site="EmbeddingService.push")
        for s, pos in self._route(ids):
            if pos.size:
                self.shards[s].push(ids[pos], grads[pos])

    # -- tier-bridge surface (routes evict/admit to the owning shard) -------

    def evict(self, ids: Sequence[int], create: bool = False) -> dict:
        ids = np.asarray(ids, np.int64).reshape(-1)
        parts = [self.shards[s].evict(ids[pos], create=create)
                 for s, pos in self._route(ids) if pos.size]
        if not parts:
            z = np.zeros((0, self.dim), np.float32)
            return {"ids": np.zeros((0,), np.int64), "rows": z,
                    "slots": z.reshape(0, 1, self.dim)[:0],
                    "steps": np.zeros((0,), np.int64)}
        out = {k: np.concatenate([p[k] for p in parts])
               for k in ("ids", "rows", "slots", "steps")}
        # restore the caller's id order (shard routing permuted it)
        order = {int(i): k for k, i in enumerate(out["ids"])}
        perm = np.asarray([order[int(i)] for i in ids
                           if int(i) in order], np.int64)
        return {k: v[perm] for k, v in out.items()}

    def admit(self, ids: Sequence[int], rows, slots=None,
              steps=None) -> None:
        ids = np.asarray(ids, np.int64).reshape(-1)
        rows = np.asarray(rows, np.float32)
        slots = None if slots is None else np.asarray(slots, np.float32)
        steps = None if steps is None else np.asarray(steps, np.int64)
        for s, pos in self._route(ids):
            if pos.size:
                self.shards[s].admit(
                    ids[pos], rows[pos],
                    None if slots is None else slots[pos],
                    None if steps is None else steps[pos])

    def state_dict(self) -> dict:
        return {"dim": self.dim, "num_shards": self.num_shards,
                "shards": [s.state_dict() for s in self.shards]}

    def load_state_dict(self, state: dict) -> None:
        for shard, sd in zip(self.shards, state["shards"]):
            shard.load_state_dict(sd)


class DistributedEmbedding:
    """Layer over :class:`EmbeddingService`: host pull → device compute →
    grad push on backward (reference distributed lookup_table /
    fleet.embedding semantics).

    Forward contracts the batch to its *unique* ids, pulls those rows once,
    and gathers on device — so both transfer and gradient are O(unique ids
    × dim). The pulled block is a differentiable leaf whose gradient hook
    pushes to the service and triggers the in-table update; no dense
    [vocab, dim] tensor ever exists on either side.

    The tape hook COALESCES before pushing: gradients from every forward
    of this layer in the batch (a model may embed two id features
    through one shared table) accumulate host-side and flush as one
    push with duplicate ids summed — so the table's optimizer takes
    exactly one step per unique id per batch, matching a dense
    ``nn.Embedding`` + optimizer at 1e-6 (the satellite parity test).
    The flush fires at the end of the full backward pass (the autograd
    engine's backward-end callback); anything left pending by a partial
    ``paddle.grad`` flushes at the next forward instead.
    """

    def __init__(self, service: EmbeddingService):
        self.service = service
        self._lock = threading.Lock()
        self._pending: List[tuple] = []  # [(uniq_ids, grads)]
        _track_for_backward_flush(self)

    def flush_grads(self) -> None:
        """Coalesce pending per-forward gradients (sum duplicates across
        forwards) and push once. Idempotent when nothing is pending."""
        with self._lock:
            pending, self._pending = self._pending, []
        if not pending:
            return
        ids = np.concatenate([p[0] for p in pending])
        grads = np.concatenate([p[1] for p in pending])
        ids, grads = _coalesce(ids, grads)
        self.service.push(ids, grads)

    def __call__(self, ids):
        import jax.numpy as jnp
        from ..core.tensor import Tensor
        from ..nn import functional as F  # noqa: F401 (tape ops)
        from ..autograd.engine import apply

        # anything still pending from a partial backward (paddle.grad
        # never reaches the backward-end callback) lands before the
        # pull below reads the rows
        self.flush_grads()
        ids_np = np.asarray(ids.numpy() if hasattr(ids, "numpy") else ids,
                            np.int64)
        uniq, inv = np.unique(ids_np.reshape(-1), return_inverse=True)
        block = self.service.pull(uniq)                      # [u, dim]
        pulled = Tensor(jnp.asarray(block), stop_gradient=False)

        def on_grad(g):
            with self._lock:
                self._pending.append((uniq, np.asarray(g.data)))
            return None

        pulled.register_hook(on_grad)
        inv_j = jnp.asarray(inv.reshape(ids_np.shape), jnp.int32)
        out = apply("dist_embedding_gather",
                    lambda w: jnp.take(w, inv_j, axis=0), (pulled,))
        self._last_pulled = pulled
        return out
