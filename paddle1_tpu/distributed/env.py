"""Distributed environment state shared across the distributed package.

Tracks (a) process-level env (rank/world size, reference
PADDLE_TRAINER_ID env protocol), and (b) the *SPMD trace context*: when a
training step is being traced under shard_map/pjit over a mesh, collective-
aware layers (SyncBatchNorm, parallel layers) must know which named mesh axis
corresponds to which logical parallelism group. This replaces the reference's
(ring_id → ncclComm_t) registry (platform/collective_helper.h:53) with
(logical axis name → mesh axis name).
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Dict, Optional

_tls = threading.local()


def get_rank() -> int:
    return int(os.environ.get("PADDLE_TRAINER_ID",
                              os.environ.get("RANK", "0")))


def get_world_size() -> int:
    return int(os.environ.get("PADDLE_TRAINERS_NUM",
                              os.environ.get("WORLD_SIZE", "1")))


@contextlib.contextmanager
def spmd_axes(**mapping: str):
    """Declare logical→mesh axis bindings for the enclosed trace, e.g.
    ``with spmd_axes(dp="data", mp="model"): ...``"""
    prev = getattr(_tls, "axes", None)
    merged = dict(prev or {})
    merged.update(mapping)
    _tls.axes = merged
    try:
        yield
    finally:
        _tls.axes = prev


def current_spmd_axis(logical: str) -> Optional[str]:
    axes = getattr(_tls, "axes", None)
    if axes is None:
        return None
    return axes.get(logical)
