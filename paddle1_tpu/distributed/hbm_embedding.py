"""Accelerator-resident embedding shards — the TPU analog of the
reference's heter_ps tier (/root/reference/paddle/fluid/framework/fleet/
heter_ps/hashtable.h:47 HashTable, heter_comm.h:50 HeterComm: GPU-HBM
embedding shards with device-side optimizers, pooled across the worker
group).

On TPU the same tier is a table row-sharded over a DATA axis of the
mesh ('sharding' by default — the pooled HBM of the dp/sharding group,
NOT the tensor-parallel axis): each chip owns ``vocab/N`` rows; lookup
runs inside jit as an owner-select + ``psum`` over ICI (O(batch × dim)
communication, the table itself never moves); the backward transposes
to a psum-free local scatter-add, so updates land directly on the
owning shard and the optimizer state shards with the rows (ZeRO-style,
via the weight's ``sharding_axes``).

Tier hierarchy matching the reference's heter_ps design:
  HBM shards (this class, hot rows, trained in-graph)
    > host-RAM EmbeddingService (ps.py, the capacity tier)
      > remote TableServers (ps_server.py, the cluster tier).
``pull``/``push_grad`` give it the same service surface as the host
tiers so callers can move a table between tiers without rewriting the
model.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..autograd.engine import apply
from ..core.errors import InvalidArgumentError
from ..core.tensor import Tensor
from ..nn.initializer import XavierUniform
from ..nn.layer_base import Layer
from . import env

__all__ = ["HBMShardedEmbedding", "hash_bucket"]


def hash_bucket(ids, buckets: int, xp=jnp):
    """Map arbitrary int feature ids onto ``[0, buckets)`` with a
    murmur3-finalizer mix — the reference hashtable's id-hash sharding
    (heter_ps/hashtable.h). Deterministic and identical between the
    jnp (in-graph) and np (host routing) forms, so the trainer's
    device lookup and the tier bridge's host bookkeeping agree on
    which bucket a feature landed in. 32-bit modular arithmetic wraps
    by construction on both backends."""
    h = xp.asarray(ids).astype(xp.uint32)
    h ^= h >> xp.uint32(16)
    h *= xp.uint32(0x85EBCA6B)
    h ^= h >> xp.uint32(13)
    h *= xp.uint32(0xC2B2AE35)
    h ^= h >> xp.uint32(16)
    return (h % xp.uint32(buckets)).astype(xp.int32)


class HBMShardedEmbedding(Layer):
    """Embedding whose table lives row-sharded in device HBM over a
    data-mesh axis (default ``'sharding'``). Under an explicit-SPMD
    region (shard_map / ParallelEngine) the lookup is the owner-select
    + psum pattern; eagerly (or on one device) it is a plain gather, so
    the layer composes with single-chip tests unchanged."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 axis: str = "sharding", axis_size: Optional[int] = None,
                 hashed: bool = False, weight_attr=None, name=None):
        super().__init__()
        if axis_size is not None and num_embeddings % axis_size:
            # pad the vocab so every shard is equal-sized (the
            # reference's hashtable shards by id hash; a fixed-capacity
            # device table pads instead)
            num_embeddings += axis_size - num_embeddings % axis_size
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._axis = axis
        # hashed mode: the table is a FIXED bucket array and incoming
        # ids are arbitrary feature hashes folded onto it in-graph
        # (reference hashtable.h semantics — vocab unbounded, capacity
        # fixed, collisions share a row)
        self._hashed = bool(hashed)
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=XavierUniform())
        self.weight.sharding_axes = (axis, None)
        self.weight.is_distributed = True

    @property
    def vocab_size(self) -> int:
        return self._num_embeddings

    @property
    def embedding_dim(self) -> int:
        return self._embedding_dim

    @property
    def hashed(self) -> bool:
        return self._hashed

    def bucketize(self, ids: Sequence[int]) -> np.ndarray:
        """Host-side twin of the in-graph hash fold (identity when not
        hashed) — what the tier bridge / input pipeline use to agree
        with the device on a feature's row."""
        ids = np.asarray(ids, np.int64)
        if not self._hashed:
            return ids
        return np.asarray(hash_bucket(ids, self._num_embeddings, xp=np),
                          np.int64)

    def forward(self, x):
        axis = self._axis
        hashed = self._hashed
        n_rows = self._num_embeddings

        def f(ids, w):
            if hashed:
                ids = hash_bucket(ids, n_rows)
            name = env.current_spmd_axis(axis)
            if name is not None and isinstance(w, jax.core.Tracer):
                # explicit-SPMD: w is the LOCAL row shard. Owner-select
                # + psum: every chip answers for its rows, zeros
                # elsewhere; the sum over the axis is the full gather.
                per = w.shape[0]
                start = lax.axis_index(name) * per
                local = ids - start
                ok = (local >= 0) & (local < per)
                safe = jnp.clip(local, 0, per - 1)
                out = jnp.where(ok[..., None], w[safe], 0.0)
                return lax.psum(out, name)
            return w[ids]

        return apply("hbm_sharded_embedding", f, (x, self.weight))

    # -- service surface (tier parity with ps.EmbeddingService) ------------

    def rows(self, slots: Sequence[int]) -> np.ndarray:
        """Raw row read by SLOT index (no hash fold, no range coddling)
        — the tier bridge / delta publisher contract."""
        slots = np.asarray(slots, np.int64).reshape(-1)
        return np.asarray(jax.device_get(self.weight.data))[slots]

    def write_rows(self, slots: Sequence[int], rows) -> None:
        """Raw row write by SLOT index (admission installs promoted
        rows; shape/dtype preserved so in-graph users never retrace)."""
        slots = np.asarray(slots, np.int64).reshape(-1)
        vals = jnp.asarray(np.asarray(rows, np.float32)
                           .reshape(slots.shape[0], self._embedding_dim))
        self.weight._data = self.weight.data.at[
            jnp.asarray(slots)].set(vals)

    def pull(self, ids: Sequence[int]) -> np.ndarray:
        """[n, dim] rows to host (the host tiers' pull contract)."""
        ids = self.bucketize(np.asarray(ids, np.int64).reshape(-1))
        if not self._hashed and ids.size and (
                int(ids.max()) >= self._num_embeddings
                or int(ids.min()) < 0):
            bad = int(ids.max()) if int(ids.max()) >= \
                self._num_embeddings else int(ids.min())
            raise InvalidArgumentError(
                f"id {bad} out of range for HBM table with "
                f"{self._num_embeddings} rows — route cold ids to the "
                "host tier (ps.EmbeddingService)")
        return np.asarray(jax.device_get(self.weight.data))[ids]

    def push_grad(self, ids: Sequence[int], grads,
                  lr: float = 0.01) -> None:
        """Host-pushed sparse SGD step (the host tiers' push contract;
        in-graph training goes through autograd instead)."""
        ids = self.bucketize(np.asarray(ids, np.int64).reshape(-1))
        g = jnp.asarray(np.asarray(grads, np.float32))
        w = self.weight.data
        self.weight._data = w.at[jnp.asarray(ids)].add(-lr * g)
