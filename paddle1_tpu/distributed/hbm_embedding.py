"""Accelerator-resident embedding shards — the TPU analog of the
reference's heter_ps tier (/root/reference/paddle/fluid/framework/fleet/
heter_ps/hashtable.h:47 HashTable, heter_comm.h:50 HeterComm: GPU-HBM
embedding shards with device-side optimizers, pooled across the worker
group).

On TPU the same tier is a table row-sharded over a DATA axis of the
mesh ('sharding' by default — the pooled HBM of the dp/sharding group,
NOT the tensor-parallel axis): each chip owns ``vocab/N`` rows; lookup
runs inside jit as an owner-select + ``psum`` over ICI (O(batch × dim)
communication, the table itself never moves); the backward transposes
to a psum-free local scatter-add, so updates land directly on the
owning shard and the optimizer state shards with the rows (ZeRO-style,
via the weight's ``sharding_axes``).

Tier hierarchy matching the reference's heter_ps design:
  HBM shards (this class, hot rows, trained in-graph)
    > host-RAM EmbeddingService (ps.py, the capacity tier)
      > remote TableServers (ps_server.py, the cluster tier).
``pull``/``push_grad`` give it the same service surface as the host
tiers so callers can move a table between tiers without rewriting the
model.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..autograd.engine import apply
from ..core.errors import InvalidArgumentError
from ..core.tensor import Tensor
from ..nn.initializer import XavierUniform
from ..nn.layer_base import Layer
from . import env

__all__ = ["HBMShardedEmbedding"]


class HBMShardedEmbedding(Layer):
    """Embedding whose table lives row-sharded in device HBM over a
    data-mesh axis (default ``'sharding'``). Under an explicit-SPMD
    region (shard_map / ParallelEngine) the lookup is the owner-select
    + psum pattern; eagerly (or on one device) it is a plain gather, so
    the layer composes with single-chip tests unchanged."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 axis: str = "sharding", axis_size: Optional[int] = None,
                 weight_attr=None, name=None):
        super().__init__()
        if axis_size is not None and num_embeddings % axis_size:
            # pad the vocab so every shard is equal-sized (the
            # reference's hashtable shards by id hash; a fixed-capacity
            # device table pads instead)
            num_embeddings += axis_size - num_embeddings % axis_size
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._axis = axis
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=XavierUniform())
        self.weight.sharding_axes = (axis, None)
        self.weight.is_distributed = True

    @property
    def vocab_size(self) -> int:
        return self._num_embeddings

    def forward(self, x):
        axis = self._axis

        def f(ids, w):
            name = env.current_spmd_axis(axis)
            if name is not None and isinstance(w, jax.core.Tracer):
                # explicit-SPMD: w is the LOCAL row shard. Owner-select
                # + psum: every chip answers for its rows, zeros
                # elsewhere; the sum over the axis is the full gather.
                per = w.shape[0]
                start = lax.axis_index(name) * per
                local = ids - start
                ok = (local >= 0) & (local < per)
                safe = jnp.clip(local, 0, per - 1)
                out = jnp.where(ok[..., None], w[safe], 0.0)
                return lax.psum(out, name)
            return w[ids]

        return apply("hbm_sharded_embedding", f, (x, self.weight))

    # -- service surface (tier parity with ps.EmbeddingService) ------------

    def pull(self, ids: Sequence[int]) -> np.ndarray:
        """[n, dim] rows to host (the host tiers' pull contract)."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        if ids.size and (int(ids.max()) >= self._num_embeddings
                         or int(ids.min()) < 0):
            bad = int(ids.max()) if int(ids.max()) >= \
                self._num_embeddings else int(ids.min())
            raise InvalidArgumentError(
                f"id {bad} out of range for HBM table with "
                f"{self._num_embeddings} rows — route cold ids to the "
                "host tier (ps.EmbeddingService)")
        return np.asarray(jax.device_get(self.weight.data))[ids]

    def push_grad(self, ids: Sequence[int], grads,
                  lr: float = 0.01) -> None:
        """Host-pushed sparse SGD step (the host tiers' push contract;
        in-graph training goes through autograd instead)."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        g = jnp.asarray(np.asarray(grads, np.float32))
        w = self.weight.data
        self.weight._data = w.at[jnp.asarray(ids)].add(-lr * g)
