"""Async dense-parameter communication for PS mode — the analog of the
reference's Communicator (/root/reference/paddle/fluid/distributed/
service/communicator.cc, communicator.h AsyncCommunicator/
GeoCommunicator): workers train while gradients stream to the parameter
server through merging send queues, and parameters refresh back
periodically, instead of the synchronous pull/push around every step.

Three pieces:

* :class:`DenseEndpoint` — uniform access to a dense block that lives
  either in-process (:class:`~paddle1_tpu.distributed.ps.DenseTable`) or
  behind a :class:`~paddle1_tpu.distributed.ps_server.RemoteTable`
  (primary or named side table).
* :class:`AsyncCommunicator` — bounded per-table send queues, a
  background thread that merges up to ``merge_num`` queued gradients
  (reference ``max_merge_var_num``) into one ``push_dense_grad``, and a
  periodic parameter pull into a local cache. ``flush()`` drains
  synchronously for deterministic shutdown/tests.
* :class:`GeoCommunicator` — geo-async SGD (reference GeoCommunicator /
  sparse_geo_table.h): the worker trains on a LOCAL copy and every
  ``geo_k`` steps pushes the accumulated parameter *delta* to the table
  (additive merge across workers) and adopts the merged value. Local
  staleness is bounded by ``geo_k`` steps by construction —
  ``steps_since_sync`` exposes the bound for verification.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Dict, Optional

import numpy as np

from ..core.errors import PreconditionNotMetError

__all__ = ["DenseEndpoint", "AsyncCommunicator", "GeoCommunicator",
           "SparseAsyncCommunicator"]

_log = logging.getLogger("paddle1_tpu.communicator")


class DenseEndpoint:
    """Adapter: DenseTable | RemoteTable | (RemoteTable, table_name)."""

    def __init__(self, target, table_name: Optional[str] = None):
        if isinstance(target, tuple):
            target, table_name = target
        self._t = target
        self._name = table_name

    def _invoke(self, method, *args):
        if hasattr(self._t, method):  # in-process DenseTable
            return getattr(self._t, method)(*args)
        if self._name is not None:
            return self._t.table_call(self._name, method, *args)
        return self._t.call(method, *args)

    def push_grad(self, grad) -> None:
        self._invoke("push_dense_grad", np.asarray(grad, np.float32))

    def push_delta(self, delta) -> None:
        self._invoke("push_dense_delta", np.asarray(delta, np.float32))

    def pull(self) -> np.ndarray:
        return np.asarray(self._invoke("pull_dense"), np.float32)

    def version(self) -> int:
        return int(self._invoke("get_version"))


class AsyncCommunicator:
    """Reference AsyncCommunicator semantics: send queues decouple the
    trainer loop from PS round-trips; queued gradients merge before the
    wire (``merge_mode`` "mean" averages like the reference's
    trainer-count scaling, "sum" adds raw)."""

    def __init__(self, tables: Dict[str, object],
                 merge_num: int = 4, merge_mode: str = "mean",
                 send_queue_size: int = 64,
                 send_interval: float = 0.002,
                 pull_interval: float = 0.05):
        if merge_mode not in ("mean", "sum"):
            raise ValueError(f"merge_mode {merge_mode!r}")
        self._eps = {n: t if isinstance(t, DenseEndpoint)
                     else DenseEndpoint(t) for n, t in tables.items()}
        self._queues: Dict[str, queue.Queue] = {
            n: queue.Queue(maxsize=send_queue_size) for n in self._eps}
        self._cache: Dict[str, np.ndarray] = {}
        self._merge_num = int(merge_num)
        self._merge_mode = merge_mode
        self._send_interval = send_interval
        self._pull_interval = pull_interval
        self._stop = threading.Event()
        self._threads = []
        self._started = False
        self._lock = threading.Lock()
        self._fatal: Optional[BaseException] = None
        self._max_retries = 5

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "AsyncCommunicator":
        if self._started:
            return self
        self._started = True
        self._stop.clear()
        for n in self._eps:
            self._cache[n] = self._eps[n].pull()
        t_send = threading.Thread(target=self._send_loop, daemon=True)
        t_pull = threading.Thread(target=self._pull_loop, daemon=True)
        self._threads = [t_send, t_pull]
        [t.start() for t in self._threads]
        return self

    def stop(self) -> None:
        if not self._started:
            return
        self.flush()
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)
        self._started = False

    # -- trainer surface ----------------------------------------------------

    def send(self, name: str, grad) -> None:
        """Enqueue one gradient (blocks when the bounded queue is full —
        the reference's send_queue_size backpressure). Raises instead of
        blocking forever if the send thread has died of repeated RPC
        failures."""
        if not self._started:
            raise PreconditionNotMetError(
                "AsyncCommunicator.send before start()")
        g = np.asarray(grad, np.float32)
        while True:
            if self._fatal is not None:
                raise PreconditionNotMetError(
                    f"AsyncCommunicator send thread is down: {self._fatal}")
            try:
                self._queues[name].put(g, timeout=1.0)
                return
            except queue.Full:
                continue  # re-check thread health, then keep waiting

    def recv(self, name: str) -> np.ndarray:
        """Latest locally-cached parameter value (refreshed by the pull
        thread; the trainer never waits on the wire)."""
        with self._lock:
            return self._cache[name].copy()

    def flush(self) -> None:
        """Drain every queue into merged pushes NOW and refresh the
        cache — the synchronization point for epoch ends and tests."""
        for n in self._eps:
            self._drain(n)
        self._pull_all()

    # -- internals ----------------------------------------------------------

    def _drain(self, name: str) -> None:
        q = self._queues[name]
        while True:
            batch = []
            while len(batch) < self._merge_num:
                try:
                    batch.append(q.get_nowait())
                except queue.Empty:
                    break
            if not batch:
                return
            merged = np.sum(batch, axis=0)
            if self._merge_mode == "mean":
                merged = merged / len(batch)
            self._eps[name].push_grad(merged)

    def _send_loop(self) -> None:
        # transient RPC failures retry with backoff (reference
        # communicator keeps sending across brpc hiccups); persistent
        # failure is recorded so send() raises instead of blocking
        # forever on a full queue
        failures = 0
        while not self._stop.is_set():
            try:
                for n in self._eps:
                    self._drain(n)
                failures = 0
            except Exception as e:
                failures += 1
                _log.warning("communicator send failed (%d/%d): %s",
                             failures, self._max_retries, e)
                if failures >= self._max_retries:
                    self._fatal = e
                    return
                time.sleep(min(0.1 * 2 ** failures, 2.0))
            time.sleep(self._send_interval)

    def _pull_all(self) -> None:
        for n, ep in self._eps.items():
            v = ep.pull()
            with self._lock:
                self._cache[n] = v

    def _pull_loop(self) -> None:
        failures = 0
        while not self._stop.is_set():
            try:
                self._pull_all()
                failures = 0
            except Exception as e:
                failures += 1
                _log.warning("communicator pull failed (%d/%d): %s",
                             failures, self._max_retries, e)
                if failures >= self._max_retries:
                    return  # recv() keeps serving the last good cache
                time.sleep(min(0.1 * 2 ** failures, 2.0))
            time.sleep(self._pull_interval)


class SparseAsyncCommunicator:
    """Async PS mode for SPARSE tables (ISSUE 19 tentpole (c)): the
    host-tier pull/push overlaps the device step instead of
    synchronizing around it — the sparse half of the reference
    AsyncCommunicator (communicator.cc SendSparse/RecvSparse).

    * ``push(ids, grads)`` enqueues one step's sparse gradient and
      returns immediately; a background thread drains the bounded
      queue, COALESCING duplicate ids across up to ``merge_num``
      queued pushes into one wire push (one in-table optimizer step
      per unique id per drain — SparseTable's own dedup handles
      within-push duplicates, this merges across steps).
    * ``prefetch(ids)`` starts pulling next step's rows concurrently;
      ``pulled(ids)`` returns them, waiting only if the prefetch
      hasn't landed.
    * **Bounded staleness**: at most ``max_staleness`` pushed-but-
      unapplied steps may be outstanding — ``push`` blocks past the
      bound (the reference's barrier on send queue depth), and
      ``staleness()`` exposes the live count for verification.
    * ``flush()`` drains synchronously (epoch end / checkpoint);
      ``state_dict`` flushes first, so the PR 2 manifest protocol
      checkpoints a quiesced stream (no gradient rides only the
      queue).
    """

    def __init__(self, service, merge_num: int = 4,
                 max_staleness: int = 8,
                 send_interval: float = 0.002):
        if max_staleness < 1:
            raise ValueError("max_staleness must be >= 1")
        self.service = service
        self._merge_num = max(1, int(merge_num))
        self.max_staleness = int(max_staleness)
        self._send_interval = float(send_interval)
        self._q: "queue.Queue" = queue.Queue()
        self._outstanding = 0            # guarded-by: self._cond
        self._cond = threading.Condition()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._fatal: Optional[BaseException] = None
        self._max_retries = 5
        self.pushed_total = 0
        self.applied_total = 0
        # prefetch: one in-flight (ids, future-rows) slot
        self._pf_lock = threading.Lock()
        self._pf_ids: Optional[np.ndarray] = None
        self._pf_rows = None
        self._pf_event = threading.Event()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "SparseAsyncCommunicator":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._send_loop,
                                        daemon=True,
                                        name="sparse-async-comm")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self.flush()
        self._stop.set()
        self._thread.join(timeout=5)

    # -- trainer surface ----------------------------------------------------

    def push(self, ids, grads) -> None:
        """Enqueue one step's sparse gradient; blocks only when the
        staleness bound is reached (backpressure, not loss)."""
        if self._thread is None or not self._thread.is_alive():
            raise PreconditionNotMetError(
                "SparseAsyncCommunicator.push before start() (or after "
                f"a fatal send error: {self._fatal!r})")
        ids = np.asarray(ids, np.int64).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(ids.shape[0], -1)
        with self._cond:
            while self._outstanding >= self.max_staleness:
                if self._fatal is not None:
                    raise PreconditionNotMetError(
                        "SparseAsyncCommunicator send thread is down: "
                        f"{self._fatal}")
                self._cond.wait(timeout=1.0)
            self._outstanding += 1
            self.pushed_total += 1
        self._q.put((ids, grads))

    def staleness(self) -> int:
        """Pushed-but-unapplied steps right now (≤ max_staleness)."""
        with self._cond:
            return self._outstanding

    def prefetch(self, ids) -> None:
        """Start pulling rows for ``ids`` concurrently with the device
        step; one slot — a new prefetch replaces an unclaimed one."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        with self._pf_lock:
            self._pf_ids, self._pf_rows = ids, None
            self._pf_event.clear()

        def _pull(want=ids):
            rows = self.service.pull(want)
            with self._pf_lock:
                if self._pf_ids is not None and \
                        np.array_equal(self._pf_ids, want):
                    self._pf_rows = rows
                    self._pf_event.set()
        threading.Thread(target=_pull, daemon=True).start()

    def pulled(self, ids, timeout: float = 30.0) -> np.ndarray:
        """Rows for ``ids``: the prefetched block when it matches,
        else a direct (synchronous) pull."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        with self._pf_lock:
            match = self._pf_ids is not None and \
                np.array_equal(self._pf_ids, ids)
        if match:
            if not self._pf_event.wait(timeout):
                raise PreconditionNotMetError(
                    f"prefetch did not land within {timeout}s")
            with self._pf_lock:
                rows, self._pf_ids, self._pf_rows = \
                    self._pf_rows, None, None
            if rows is not None:
                return rows
        return self.service.pull(ids)

    def flush(self) -> None:
        """Apply every queued push NOW (synchronous barrier)."""
        self._drain(limit=None)
        with self._cond:
            if self._fatal is not None:
                raise PreconditionNotMetError(
                    f"SparseAsyncCommunicator: {self._fatal}")

    # -- persistence (quiesce, then delegate to the service) ----------------

    def state_dict(self) -> dict:
        self.flush()
        return {"service": self.service.state_dict(),
                "pushed_total": self.pushed_total,
                "applied_total": self.applied_total}

    def load_state_dict(self, state: dict) -> None:
        self.flush()
        self.service.load_state_dict(state["service"])
        self.pushed_total = int(state.get("pushed_total", 0))
        self.applied_total = int(state.get("applied_total", 0))

    # -- internals ----------------------------------------------------------

    def _drain(self, limit: Optional[int]) -> None:
        """Pop up to ``limit`` (None = all) queued pushes, coalesce
        duplicate ids across them, and push once."""
        batch = []
        while limit is None or len(batch) < limit:
            try:
                batch.append(self._q.get_nowait())
            except queue.Empty:
                break
        if not batch:
            return
        ids = np.concatenate([b[0] for b in batch])
        grads = np.concatenate([b[1] for b in batch])
        try:
            self.service.push(ids, grads)
        except BaseException:
            with self._cond:   # free the backpressure before retrying
                self._outstanding -= len(batch)
                self._cond.notify_all()
            raise
        with self._cond:
            self._outstanding -= len(batch)
            self.applied_total += len(batch)
            self._cond.notify_all()

    def _send_loop(self) -> None:
        failures = 0
        while not self._stop.is_set():
            try:
                self._drain(limit=self._merge_num)
                failures = 0
            except Exception as e:
                failures += 1
                _log.warning("sparse communicator push failed "
                             "(%d/%d): %s", failures,
                             self._max_retries, e)
                if failures >= self._max_retries:
                    self._fatal = e
                    with self._cond:
                        self._cond.notify_all()
                    return
                time.sleep(min(0.1 * 2 ** failures, 2.0))
            time.sleep(self._send_interval)


class GeoCommunicator:
    """Geo-async SGD: train locally, sync deltas every ``geo_k`` steps.
    The PS merges deltas additively across workers (DenseTable.
    push_dense_delta), so concurrent workers compose like the
    reference's geo tables; each worker's staleness relative to the PS
    is bounded by ``geo_k`` of its own steps."""

    def __init__(self, tables: Dict[str, object], geo_k: int = 8):
        if geo_k < 1:
            raise ValueError("geo_k must be >= 1")
        self._eps = {n: t if isinstance(t, DenseEndpoint)
                     else DenseEndpoint(t) for n, t in tables.items()}
        self.geo_k = int(geo_k)
        self._base: Dict[str, np.ndarray] = {}
        self._steps: Dict[str, int] = {}

    def register(self, name: str) -> np.ndarray:
        """Adopt the table's current value as the local working copy."""
        v = self._eps[name].pull()
        self._base[name] = v.copy()
        self._steps[name] = 0
        return v

    def steps_since_sync(self, name: str) -> int:
        return self._steps[name]

    def step(self, name: str, local_value) -> np.ndarray:
        """Record one local training step on ``name``; on every
        ``geo_k``-th step push the accumulated delta and adopt the
        merged table value. Returns the value the worker should continue
        from."""
        if name not in self._base:
            raise PreconditionNotMetError(
                f"GeoCommunicator.step({name!r}) before register()")
        local_value = np.asarray(local_value, np.float32)
        self._steps[name] += 1
        if self._steps[name] < self.geo_k:
            return local_value
        ep = self._eps[name]
        ep.push_delta(local_value - self._base[name])
        merged = ep.pull()
        self._base[name] = merged.copy()
        self._steps[name] = 0
        return merged
