"""Async dense-parameter communication for PS mode — the analog of the
reference's Communicator (/root/reference/paddle/fluid/distributed/
service/communicator.cc, communicator.h AsyncCommunicator/
GeoCommunicator): workers train while gradients stream to the parameter
server through merging send queues, and parameters refresh back
periodically, instead of the synchronous pull/push around every step.

Three pieces:

* :class:`DenseEndpoint` — uniform access to a dense block that lives
  either in-process (:class:`~paddle1_tpu.distributed.ps.DenseTable`) or
  behind a :class:`~paddle1_tpu.distributed.ps_server.RemoteTable`
  (primary or named side table).
* :class:`AsyncCommunicator` — bounded per-table send queues, a
  background thread that merges up to ``merge_num`` queued gradients
  (reference ``max_merge_var_num``) into one ``push_dense_grad``, and a
  periodic parameter pull into a local cache. ``flush()`` drains
  synchronously for deterministic shutdown/tests.
* :class:`GeoCommunicator` — geo-async SGD (reference GeoCommunicator /
  sparse_geo_table.h): the worker trains on a LOCAL copy and every
  ``geo_k`` steps pushes the accumulated parameter *delta* to the table
  (additive merge across workers) and adopts the merged value. Local
  staleness is bounded by ``geo_k`` steps by construction —
  ``steps_since_sync`` exposes the bound for verification.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Dict, Optional

import numpy as np

from ..core.errors import PreconditionNotMetError

__all__ = ["DenseEndpoint", "AsyncCommunicator", "GeoCommunicator"]

_log = logging.getLogger("paddle1_tpu.communicator")


class DenseEndpoint:
    """Adapter: DenseTable | RemoteTable | (RemoteTable, table_name)."""

    def __init__(self, target, table_name: Optional[str] = None):
        if isinstance(target, tuple):
            target, table_name = target
        self._t = target
        self._name = table_name

    def _invoke(self, method, *args):
        if hasattr(self._t, method):  # in-process DenseTable
            return getattr(self._t, method)(*args)
        if self._name is not None:
            return self._t.table_call(self._name, method, *args)
        return self._t.call(method, *args)

    def push_grad(self, grad) -> None:
        self._invoke("push_dense_grad", np.asarray(grad, np.float32))

    def push_delta(self, delta) -> None:
        self._invoke("push_dense_delta", np.asarray(delta, np.float32))

    def pull(self) -> np.ndarray:
        return np.asarray(self._invoke("pull_dense"), np.float32)

    def version(self) -> int:
        return int(self._invoke("get_version"))


class AsyncCommunicator:
    """Reference AsyncCommunicator semantics: send queues decouple the
    trainer loop from PS round-trips; queued gradients merge before the
    wire (``merge_mode`` "mean" averages like the reference's
    trainer-count scaling, "sum" adds raw)."""

    def __init__(self, tables: Dict[str, object],
                 merge_num: int = 4, merge_mode: str = "mean",
                 send_queue_size: int = 64,
                 send_interval: float = 0.002,
                 pull_interval: float = 0.05):
        if merge_mode not in ("mean", "sum"):
            raise ValueError(f"merge_mode {merge_mode!r}")
        self._eps = {n: t if isinstance(t, DenseEndpoint)
                     else DenseEndpoint(t) for n, t in tables.items()}
        self._queues: Dict[str, queue.Queue] = {
            n: queue.Queue(maxsize=send_queue_size) for n in self._eps}
        self._cache: Dict[str, np.ndarray] = {}
        self._merge_num = int(merge_num)
        self._merge_mode = merge_mode
        self._send_interval = send_interval
        self._pull_interval = pull_interval
        self._stop = threading.Event()
        self._threads = []
        self._started = False
        self._lock = threading.Lock()
        self._fatal: Optional[BaseException] = None
        self._max_retries = 5

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "AsyncCommunicator":
        if self._started:
            return self
        self._started = True
        self._stop.clear()
        for n in self._eps:
            self._cache[n] = self._eps[n].pull()
        t_send = threading.Thread(target=self._send_loop, daemon=True)
        t_pull = threading.Thread(target=self._pull_loop, daemon=True)
        self._threads = [t_send, t_pull]
        [t.start() for t in self._threads]
        return self

    def stop(self) -> None:
        if not self._started:
            return
        self.flush()
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)
        self._started = False

    # -- trainer surface ----------------------------------------------------

    def send(self, name: str, grad) -> None:
        """Enqueue one gradient (blocks when the bounded queue is full —
        the reference's send_queue_size backpressure). Raises instead of
        blocking forever if the send thread has died of repeated RPC
        failures."""
        if not self._started:
            raise PreconditionNotMetError(
                "AsyncCommunicator.send before start()")
        g = np.asarray(grad, np.float32)
        while True:
            if self._fatal is not None:
                raise PreconditionNotMetError(
                    f"AsyncCommunicator send thread is down: {self._fatal}")
            try:
                self._queues[name].put(g, timeout=1.0)
                return
            except queue.Full:
                continue  # re-check thread health, then keep waiting

    def recv(self, name: str) -> np.ndarray:
        """Latest locally-cached parameter value (refreshed by the pull
        thread; the trainer never waits on the wire)."""
        with self._lock:
            return self._cache[name].copy()

    def flush(self) -> None:
        """Drain every queue into merged pushes NOW and refresh the
        cache — the synchronization point for epoch ends and tests."""
        for n in self._eps:
            self._drain(n)
        self._pull_all()

    # -- internals ----------------------------------------------------------

    def _drain(self, name: str) -> None:
        q = self._queues[name]
        while True:
            batch = []
            while len(batch) < self._merge_num:
                try:
                    batch.append(q.get_nowait())
                except queue.Empty:
                    break
            if not batch:
                return
            merged = np.sum(batch, axis=0)
            if self._merge_mode == "mean":
                merged = merged / len(batch)
            self._eps[name].push_grad(merged)

    def _send_loop(self) -> None:
        # transient RPC failures retry with backoff (reference
        # communicator keeps sending across brpc hiccups); persistent
        # failure is recorded so send() raises instead of blocking
        # forever on a full queue
        failures = 0
        while not self._stop.is_set():
            try:
                for n in self._eps:
                    self._drain(n)
                failures = 0
            except Exception as e:
                failures += 1
                _log.warning("communicator send failed (%d/%d): %s",
                             failures, self._max_retries, e)
                if failures >= self._max_retries:
                    self._fatal = e
                    return
                time.sleep(min(0.1 * 2 ** failures, 2.0))
            time.sleep(self._send_interval)

    def _pull_all(self) -> None:
        for n, ep in self._eps.items():
            v = ep.pull()
            with self._lock:
                self._cache[n] = v

    def _pull_loop(self) -> None:
        failures = 0
        while not self._stop.is_set():
            try:
                self._pull_all()
                failures = 0
            except Exception as e:
                failures += 1
                _log.warning("communicator pull failed (%d/%d): %s",
                             failures, self._max_retries, e)
                if failures >= self._max_retries:
                    return  # recv() keeps serving the last good cache
                time.sleep(min(0.1 * 2 ** failures, 2.0))
            time.sleep(self._pull_interval)


class GeoCommunicator:
    """Geo-async SGD: train locally, sync deltas every ``geo_k`` steps.
    The PS merges deltas additively across workers (DenseTable.
    push_dense_delta), so concurrent workers compose like the
    reference's geo tables; each worker's staleness relative to the PS
    is bounded by ``geo_k`` of its own steps."""

    def __init__(self, tables: Dict[str, object], geo_k: int = 8):
        if geo_k < 1:
            raise ValueError("geo_k must be >= 1")
        self._eps = {n: t if isinstance(t, DenseEndpoint)
                     else DenseEndpoint(t) for n, t in tables.items()}
        self.geo_k = int(geo_k)
        self._base: Dict[str, np.ndarray] = {}
        self._steps: Dict[str, int] = {}

    def register(self, name: str) -> np.ndarray:
        """Adopt the table's current value as the local working copy."""
        v = self._eps[name].pull()
        self._base[name] = v.copy()
        self._steps[name] = 0
        return v

    def steps_since_sync(self, name: str) -> int:
        return self._steps[name]

    def step(self, name: str, local_value) -> np.ndarray:
        """Record one local training step on ``name``; on every
        ``geo_k``-th step push the accumulated delta and adopt the
        merged table value. Returns the value the worker should continue
        from."""
        if name not in self._base:
            raise PreconditionNotMetError(
                f"GeoCommunicator.step({name!r}) before register()")
        local_value = np.asarray(local_value, np.float32)
        self._steps[name] += 1
        if self._steps[name] < self.geo_k:
            return local_value
        ep = self._eps[name]
        ep.push_delta(local_value - self._base[name])
        merged = ep.pull()
        self._base[name] = merged.copy()
        self._steps[name] = 0
        return merged
