"""Graph table — the graph-learning member of the PS table family.

Reference analog: ``common_graph_table.h`` in
/root/reference/paddle/fluid/distributed/table/ (GraphTable: adjacency
lists with weighted neighbor sampling + per-node features, served by the
brpc PS for distributed GNN training). Scoped the same way as the sparse
table (SURVEY §7f): the graph lives in host RAM beside the input
pipeline; the device mesh only ever sees the dense sampled id/feature
batches.

Weighted sampling uses per-node cumulative weights + binary search —
the numpy twin of the reference's WeightedSampler
(table/weighted_sampler.cc).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence

import numpy as np

__all__ = ["GraphTable"]


class GraphTable:
    """Host-RAM adjacency + node features with weighted neighbor
    sampling. Thread-safe; servable via ps_server.TableServer (the
    RPC_METHODS whitelist is the remote surface)."""

    RPC_METHODS = frozenset({
        "add_edges", "sample_neighbors", "node_degree", "num_nodes",
        "num_edges", "set_node_feat", "get_node_feat", "random_walk",
        "pull", "push",
    })

    def __init__(self, seed: int = 0, feat_dim: int = 0,
                 feat_lr: float = 0.01):
        # width handshake: 0 = no trainable feature surface; > 0 makes
        # this table servable behind EmbeddingService like a sparse
        # shard (GNN node features as the coldest tier)
        self.dim = int(feat_dim)
        self.feat_lr = float(feat_lr)
        self._adj: Dict[int, list] = {}        # id -> [nbr ids]
        self._w: Dict[int, list] = {}          # id -> [weights]
        self._cum: Dict[int, tuple] = {}       # id -> (nbr arr, cumsum)
        self._feat: Dict[int, np.ndarray] = {}
        self._n_edges = 0
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()

    # -- sparse-table protocol (pull/push over node features) ---------------

    def pull(self, ids: Sequence[int]) -> np.ndarray:
        """Node features under the SparseTable pull contract (zeros for
        absent nodes) — lets a GraphTable sit behind EmbeddingService /
        the tier bridge as a feature source."""
        if not self.dim:
            raise ValueError(
                "GraphTable.pull needs feat_dim > 0 at construction "
                "(the embedding-width handshake)")
        return self.get_node_feat(ids, self.dim)

    def push(self, ids: Sequence[int], grads) -> None:
        """SGD step on node features (the feature-learning half of the
        reference's GNN PS mode). Duplicate ids coalesce like
        SparseTable.push."""
        if not self.dim:
            raise ValueError("GraphTable.push needs feat_dim > 0")
        from .ps import _coalesce
        ids, grads = _coalesce(ids, grads)
        with self._lock:
            for k, i in enumerate(ids):
                i = int(i)
                f = self._feat.get(i)
                if f is None:
                    f = np.zeros(self.dim, np.float32)
                    self._feat[i] = f
                f -= self.feat_lr * grads[k]

    # -- construction -------------------------------------------------------

    def add_edges(self, src: Sequence[int], dst: Sequence[int],
                  weights: Optional[Sequence[float]] = None) -> None:
        src = np.asarray(src, np.int64).reshape(-1)
        dst = np.asarray(dst, np.int64).reshape(-1)
        if src.shape != dst.shape:
            raise ValueError("src and dst must have the same length")
        w = (np.asarray(weights, np.float64).reshape(-1)
             if weights is not None else np.ones(src.shape[0]))
        if w.shape != src.shape:
            raise ValueError("weights must match src length")
        if np.any(w <= 0):
            raise ValueError("edge weights must be positive")
        with self._lock:
            for s, d, wt in zip(src, dst, w):
                s = int(s)
                self._adj.setdefault(s, []).append(int(d))
                self._w.setdefault(s, []).append(float(wt))
                self._cum.pop(s, None)  # invalidate the sampler cache
            self._n_edges += src.shape[0]

    # -- queries ------------------------------------------------------------

    def num_nodes(self) -> int:
        with self._lock:
            return len(set(self._adj) | set(self._feat))

    def num_edges(self) -> int:
        return self._n_edges

    def node_degree(self, ids: Sequence[int]) -> np.ndarray:
        with self._lock:
            return np.asarray([len(self._adj.get(int(i), ()))
                               for i in np.asarray(ids).reshape(-1)],
                              np.int64)

    def _sampler(self, i: int):
        """(neighbor int64 array, cumulative weights) — both cached; the
        hot sampling path must not rebuild arrays under the lock."""
        c = self._cum.get(i)
        if c is None:
            c = (np.asarray(self._adj[i], np.int64),
                 np.cumsum(np.asarray(self._w[i], np.float64)))
            self._cum[i] = c
        return c

    def sample_neighbors(self, ids: Sequence[int], sample_size: int,
                         seed: Optional[int] = None) -> np.ndarray:
        """[len(ids), sample_size] int64, weighted WITH replacement
        (reference graph_table random_sample_neighbors semantics);
        nodes without outgoing edges pad with -1."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        out = np.full((ids.shape[0], int(sample_size)), -1, np.int64)
        rng = np.random.default_rng(seed) if seed is not None else self._rng
        with self._lock:
            for r, i in enumerate(ids):
                i = int(i)
                if not self._adj.get(i):
                    continue
                nbrs, cum = self._sampler(i)
                u = rng.random(int(sample_size)) * cum[-1]
                # u == cum[-1] is possible (rng.random() can round to the
                # top); clamp like np.random.choice does
                idx = np.minimum(np.searchsorted(cum, u, side="right"),
                                 len(cum) - 1)
                out[r] = nbrs[idx]
        return out

    def random_walk(self, ids: Sequence[int], walk_len: int,
                    seed: Optional[int] = None) -> np.ndarray:
        """[len(ids), walk_len + 1] weighted random walks; a walk that
        reaches a sink stays there (-1 padding for the remainder)."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        walks = np.full((ids.shape[0], int(walk_len) + 1), -1, np.int64)
        walks[:, 0] = ids
        cur = ids
        for t in range(1, int(walk_len) + 1):
            step = self.sample_neighbors(cur, 1, seed=None if seed is None
                                         else seed + t)[:, 0]
            alive = (cur >= 0) & (step >= 0)
            nxt = np.where(alive, step, -1)
            walks[:, t] = nxt
            cur = nxt
        return walks

    # -- node features ------------------------------------------------------

    def set_node_feat(self, ids: Sequence[int], feats) -> None:
        ids = np.asarray(ids, np.int64).reshape(-1)
        feats = np.asarray(feats, np.float32)
        if feats.ndim != 2 or feats.shape[0] != ids.shape[0]:
            raise ValueError("feats must be [len(ids), feat_dim]")
        with self._lock:
            for k, i in enumerate(ids):
                self._feat[int(i)] = feats[k].copy()

    def get_node_feat(self, ids: Sequence[int],
                      feat_dim: Optional[int] = None) -> np.ndarray:
        ids = np.asarray(ids, np.int64).reshape(-1)
        with self._lock:
            if feat_dim is None:
                if not self._feat:
                    raise ValueError("no features stored and no feat_dim")
                feat_dim = next(iter(self._feat.values())).shape[0]
            out = np.zeros((ids.shape[0], int(feat_dim)), np.float32)
            for k, i in enumerate(ids):
                f = self._feat.get(int(i))
                if f is not None:
                    out[k] = f
        return out

    # -- persistence (same contract as SparseTable) -------------------------

    def state_dict(self) -> dict:
        with self._lock:
            return {"adj": {i: list(v) for i, v in self._adj.items()},
                    "w": {i: list(v) for i, v in self._w.items()},
                    "feat": {i: f.copy() for i, f in self._feat.items()},
                    "n_edges": self._n_edges}

    def load_state_dict(self, state: dict) -> None:
        with self._lock:
            self._adj = {int(i): list(v)
                         for i, v in state["adj"].items()}
            self._w = {int(i): list(v) for i, v in state["w"].items()}
            self._feat = {int(i): np.asarray(f, np.float32)
                          for i, f in state["feat"].items()}
            self._cum = {}
            self._n_edges = int(state["n_edges"])
