"""Process/parallel environment bootstrap + dygraph DataParallel.

Analog of the reference's ``python/paddle/distributed/parallel.py:60``
(init_parallel_env: Gloo rendezvous + NCCLParallelContext comm-ring init) and
``python/paddle/fluid/dygraph/parallel.py:380`` (DataParallel + C++ Reducer
gradient bucketing, imperative/reducer.cc).

TPU-native design: there are no per-rank NCCL rings to bootstrap. A single
process drives all local TPU chips through XLA; multi-host jobs call
``jax.distributed.initialize`` (the PJRT coordination service replaces the
reference's raw-TCP ncclUniqueId broadcast, gen_comm_id_helper.cc). Gradient
synchronization is not a bucketed background Reducer — under jit the grads
are averaged with one ``psum`` per (fused) gradient tree and XLA's
latency-hiding scheduler overlaps the collective with remaining backward
compute, which is exactly what the Reducer's bucket-overlap machinery was
hand-building.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp

from ..core.errors import PreconditionNotMetError
from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from . import env
from .collective import all_reduce, ReduceOp

__all__ = ["init_parallel_env", "get_rank", "get_world_size", "ParallelEnv",
           "DataParallel"]


_initialized = [False]


def init_parallel_env(strategy=None):
    """Bootstrap distributed state (reference parallel.py:60). On TPU:
    initialize the JAX coordination service when launched multi-process
    (env `PADDLE_TRAINER_ENDPOINTS`/standard JAX envs), else no-op."""
    if _initialized[0]:
        return ParallelEnv()
    endpoints = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
    nranks = env.get_world_size()
    # NOTE: do not touch jax.devices()/process_count() before initialize —
    # instantiating the backend first makes initialize() unusable.
    if nranks > 1 and endpoints:
        coordinator = endpoints.split(",")[0]
        from ..core import flags as core_flags
        try:
            try:
                jax.distributed.initialize(
                    coordinator_address=coordinator,
                    num_processes=nranks,
                    process_id=env.get_rank(),
                    initialization_timeout=int(
                        core_flags.flag("collective_timeout_s")))
            except TypeError:  # older jax: no timeout parameter
                jax.distributed.initialize(
                    coordinator_address=coordinator,
                    num_processes=nranks,
                    process_id=env.get_rank())
        except RuntimeError as e:
            # "already initialized" is fine (launcher or user did it);
            # anything else means the multi-host bootstrap FAILED and
            # training would silently fork into independent worlds.
            if "already" not in str(e).lower():
                raise PreconditionNotMetError(
                    f"jax.distributed.initialize failed for a "
                    f"{nranks}-process job (coordinator {coordinator}): "
                    f"{e}. Refusing to continue single-process.") from e
    _initialized[0] = True
    return ParallelEnv()


def get_rank() -> int:
    return env.get_rank()


def get_world_size() -> int:
    return env.get_world_size()


class ParallelEnv:
    """Reference fluid/dygraph/parallel.py ParallelEnv: rank/world-size/
    endpoint view of the launch env."""

    @property
    def rank(self) -> int:
        return env.get_rank()

    @property
    def local_rank(self) -> int:
        return int(os.environ.get("PADDLE_RANK_IN_NODE", str(env.get_rank())))

    @property
    def world_size(self) -> int:
        return env.get_world_size()

    @property
    def nranks(self) -> int:
        return env.get_world_size()

    @property
    def device_id(self) -> int:
        return self.local_rank

    @property
    def current_endpoint(self) -> str:
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6170")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else ["127.0.0.1:6170"]


class DataParallel(Layer):
    """Data-parallel model wrapper (reference dygraph/parallel.py:380).

    The reference attaches a C++ Reducer that buckets grads and all-reduces
    each bucket as backward marks it ready. Here the wrapper (a) marks
    parameters as distributed, (b) under an SPMD trace averages gradients
    over the dp axis via a psum hook on each parameter, and (c) in eager
    single-process mode is a transparent passthrough. Loss scaling follows
    scale_loss (parallel.py:586): identity, since psum-mean already divides.
    """

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size: int
                 = 25, last_comm_buffer_size: int = 1,
                 find_unused_parameters: bool = False, group=None,
                 comm_dtype=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters
        self._group = group
        self._grad_sync_enabled = True
        # fp16_allreduce analog (reference fp16_allreduce_optimizer.py):
        # cast the gradient to a narrow dtype for the mean-reduce, cast
        # back after — halves grad-comm bytes on the wire
        self._comm_dtype = jnp.dtype(comm_dtype) if comm_dtype else None
        for p in layers.parameters():
            p.is_distributed = True
        # grad-sync hooks: fire during backward, psum-mean over dp axis when
        # tracing SPMD; no-op otherwise (world size 1 eager)
        self._hook_handles = []
        for p in layers.parameters():
            if not p.stop_gradient:
                self._hook_handles.append(
                    p.register_hook(self._make_grad_sync_hook()))

    def _make_grad_sync_hook(self):
        def hook(grad):
            if not self._grad_sync_enabled:
                return grad
            axis = env.current_spmd_axis("dp")
            if axis is None:
                return grad
            from jax import lax
            import jax.core as jcore
            from ..autograd.engine import apply as _apply

            cdt = self._comm_dtype

            def f(g):
                if isinstance(g, jcore.Tracer):
                    if cdt is not None and jnp.issubdtype(g.dtype,
                                                          jnp.floating):
                        return lax.pmean(g.astype(cdt), axis).astype(g.dtype)
                    return lax.pmean(g, axis)
                return g
            return _apply("dp_grad_sync", f, (grad,))
        return hook

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss: Tensor) -> Tensor:
        """Reference parallel.py:586 — divide by nranks before backward so
        summed grads average. With pmean-based sync this is identity."""
        return loss

    def apply_collective_grads(self):
        """Reference parallel.py:595 manual grad allreduce (used with
        no_sync). Eagerly all-reduces each param grad over dp."""
        for p in self._layers.parameters():
            if p.grad is not None:
                all_reduce(p.grad, op=ReduceOp.AVG)

    def no_sync(self):
        """Suppress grad sync inside the context (reference parallel.py
        no_sync — used for gradient accumulation); call
        apply_collective_grads() after the last micro-batch."""
        import contextlib

        @contextlib.contextmanager
        def ctx():
            prev = self._grad_sync_enabled
            self._grad_sync_enabled = False
            try:
                yield
            finally:
                self._grad_sync_enabled = prev
        return ctx()

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    set_dict = set_state_dict
