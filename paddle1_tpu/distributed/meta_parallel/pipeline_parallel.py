"""PipelineParallel training wrapper.

Analog of the reference's dygraph ``PipelineParallel``
(python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py:43;
micro-batch loop train_batch:98, P2P activation/grad exchange :265-301) and
the C++ 1F1B ``SectionWorker`` (framework/section_worker.cc:143-181).

TPU-native: the reference interprets the schedule at runtime, sending
activations over NCCL P2P between per-stage processes. Under XLA the whole
1F1B schedule must live *inside one compiled program* (SURVEY §7 hard part
b); that in-graph schedule — lax.scan over microbatches with ppermute
neighbor exchange on the pp axis — is implemented in
``paddle1_tpu.distributed.pipeline``. This wrapper provides the reference's
``train_batch`` API: it splits the batch into micro-batches and accumulates
gradients (gradient-merge semantics, mathematically identical to the
schedule; the in-graph path is engaged when the step is jitted over a mesh
with pp degree > 1).
"""

from __future__ import annotations

from typing import List, Optional

from ...core.errors import InvalidArgumentError
from ...nn.layer_base import Layer
from ..parallel import DataParallel
from .pp_layers import PipelineLayer

__all__ = ["PipelineParallel"]


class PipelineParallel(DataParallel):
    def __init__(self, layers: Layer, hcg, strategy=None):
        if not isinstance(layers, PipelineLayer):
            raise InvalidArgumentError(
                "PipelineParallel expects a PipelineLayer model "
                "(reference pipeline_parallel.py asserts the same)")
        super().__init__(layers, group=hcg.get_data_parallel_group())
        self._hcg = hcg
        cfg = (strategy.pipeline_configs if strategy is not None else
               {"accumulate_steps": 1, "micro_batch_size": 1})
        self.accumulate_steps = cfg.get("accumulate_steps", 1)
        self.micro_batch_size = cfg.get("micro_batch_size", 1)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Reference pipeline_parallel.py:98. data = [inputs, labels].

        Runs the true 1F1B schedule over the PipelineLayer's heterogeneous
        stage partition (the SectionWorker analog, section_worker.cc:
        143-181): per-stage forward/backward segments interleave so stage
        ``s`` never holds more than ``num_stages - s`` in-flight
        microbatch activations — the bound the reference's
        max_outstanding enforces. Activations move between stages as
        detached leaves; the tape runs each segment's backward when the
        downstream grad arrives. Gradients accumulate on parameters
        exactly as sequential grad-accumulation would, so the result is
        numerically identical while activation lifetime is bounded.
        """
        inputs, labels = data
        total = inputs.shape[0]
        micro = max(1, self.micro_batch_size)
        if total % micro != 0:
            raise InvalidArgumentError(
                f"batch size {total} must be divisible by "
                f"micro_batch_size {micro} (the reference asserts the "
                f"same in pipeline_parallel.py)")
        n_micro = total // micro
        loss_fn = self._layers._loss_fn
        if loss_fn is None:
            raise InvalidArgumentError(
                "PipelineLayer needs loss_fn for train_batch")

        total_loss = self._run_1f1b(inputs, labels, n_micro, micro,
                                    loss_fn, scaler)

        if scaler is not None:
            # GradScaler.step() already advances the loss-scale state.
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total_loss / float(n_micro)

    def _run_1f1b(self, inputs, labels, n_micro, micro, loss_fn, scaler):
        from collections import deque

        from ...autograd.engine import run_backward
        from ...core.tensor import Tensor

        import jax.numpy as jnp

        S = self._layers.num_stages
        bounds = self._layers.segment_parts
        run_fn = list(self._layers.run_function)
        rc_k = self._layers.recompute_interval

        def seg_forward(s, x):
            # honor recompute_interval with the GLOBAL layer index, same
            # as PipelineLayer.forward does on the sequential path
            for gi in range(bounds[s], bounds[s + 1]):
                lyr = run_fn[gi]
                if rc_k > 0 and gi % rc_k == 0 and self._layers.training:
                    from ..fleet.utils.recompute import recompute
                    x = recompute(lyr, *x) if isinstance(x, tuple) \
                        else recompute(lyr, x)
                else:
                    x = lyr(*x) if isinstance(x, tuple) else lyr(x)
            return x

        def as_tuple(v):
            return v if isinstance(v, tuple) else (v,)

        def make_leaves(s, act):
            """Detached per-element leaves for a stage input; float leaves
            (beyond stage 0) carry grads back across the boundary."""
            leaves = []
            for el in as_tuple(act):
                d = el.data if isinstance(el, Tensor) else el
                is_f = jnp.issubdtype(jnp.result_type(d), jnp.floating)
                leaves.append(Tensor(d, stop_gradient=not (is_f and s > 0)))
            return tuple(leaves)

        input_q = [deque() for _ in range(S)]   # (mb, activation tuple)
        grad_q = [deque() for _ in range(S)]    # (mb, out-grad tuple|None)
        inflight = [{} for _ in range(S)]       # mb -> (leaves, out/loss)
        fwd_done = [0] * S
        bwd_done = [0] * S
        self.last_max_in_flight = [0] * S
        for i in range(n_micro):
            input_q[0].append((i, inputs[i * micro:(i + 1) * micro]))
        total_loss = None

        def do_forward(s):
            mb, x = input_q[s].popleft()
            leaves = make_leaves(s, x)
            out = seg_forward(s, leaves if len(leaves) > 1 else leaves[0])
            if s == S - 1:
                y = labels[mb * micro:(mb + 1) * micro]
                loss = loss_fn(out, y)
                inflight[s][mb] = (leaves, loss)
                grad_q[s].append((mb, None))    # own bwd is now runnable
            else:
                inflight[s][mb] = (leaves, out)
                handoff = tuple(o.detach() if isinstance(o, Tensor) else o
                                for o in as_tuple(out))
                input_q[s + 1].append(
                    (mb, handoff if len(handoff) > 1 else handoff[0]))
            fwd_done[s] += 1
            self.last_max_in_flight[s] = max(
                self.last_max_in_flight[s], fwd_done[s] - bwd_done[s])

        def do_backward(s):
            nonlocal total_loss
            mb, g = grad_q[s].popleft()
            leaves, out = inflight[s].pop(mb)
            if s == S - 1:
                scaled = out / float(n_micro)
                if scaler is not None:
                    scaler.scale(scaled).backward()
                else:
                    scaled.backward()
                total_loss = out.detach() if total_loss is None \
                    else total_loss + out.detach()
            elif g is not None:
                # back-propagate only the outputs a grad arrived for
                outs = as_tuple(out)
                pairs = [(o, gg) for o, gg in zip(outs, as_tuple(g))
                         if gg is not None and isinstance(o, Tensor)
                         and not o.stop_gradient]
                if pairs:
                    run_backward([o for o, _ in pairs],
                                 [gg for _, gg in pairs])
            # ALWAYS hand something upstream, else a non-differentiable
            # boundary (int ids, detached features) starves the upstream
            # queue and the schedule deadlocks
            if s > 0:
                gs = tuple(l.grad if not l.stop_gradient else None
                           for l in leaves)
                grad_q[s - 1].append(
                    (mb, None if all(x is None for x in gs) else gs))
            bwd_done[s] += 1

        # event loop: each pass gives every stage one op — backward when a
        # grad is waiting (frees memory), else forward within the 1F1B
        # in-flight bound (stage s holds at most S - s microbatches)
        while any(b < n_micro for b in bwd_done):
            progressed = False
            for s in range(S - 1, -1, -1):
                if grad_q[s] and fwd_done[s] > bwd_done[s]:
                    do_backward(s)
                    progressed = True
                elif input_q[s] and fwd_done[s] < n_micro and \
                        (fwd_done[s] - bwd_done[s]) < (S - s):
                    do_forward(s)
                    progressed = True
            if not progressed:  # pragma: no cover - schedule invariant
                raise RuntimeError("1F1B schedule deadlocked")
        return total_loss

    def eval_batch(self, data, compute_loss: bool = True):
        inputs, labels = data
        out = self._layers(inputs)
        if compute_loss and self._layers._loss_fn is not None:
            return self._layers._loss_fn(out, labels)
        return out
