"""PipelineParallel training wrapper.

Analog of the reference's dygraph ``PipelineParallel``
(python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py:43;
micro-batch loop train_batch:98, P2P activation/grad exchange :265-301) and
the C++ 1F1B ``SectionWorker`` (framework/section_worker.cc:143-181).

TPU-native: the reference interprets the schedule at runtime, sending
activations over NCCL P2P between per-stage processes. Under XLA the whole
1F1B schedule must live *inside one compiled program* (SURVEY §7 hard part
b); that in-graph schedule — lax.scan over microbatches with ppermute
neighbor exchange on the pp axis — is implemented in
``paddle1_tpu.distributed.pipeline``. This wrapper provides the reference's
``train_batch`` API: it splits the batch into micro-batches and accumulates
gradients (gradient-merge semantics, mathematically identical to the
schedule; the in-graph path is engaged when the step is jitted over a mesh
with pp degree > 1).
"""

from __future__ import annotations

from typing import List, Optional

from ...core.errors import InvalidArgumentError
from ...nn.layer_base import Layer
from ..parallel import DataParallel
from .pp_layers import PipelineLayer

__all__ = ["PipelineParallel"]


class PipelineParallel(DataParallel):
    def __init__(self, layers: Layer, hcg, strategy=None):
        if not isinstance(layers, PipelineLayer):
            raise InvalidArgumentError(
                "PipelineParallel expects a PipelineLayer model "
                "(reference pipeline_parallel.py asserts the same)")
        super().__init__(layers, group=hcg.get_data_parallel_group())
        self._hcg = hcg
        cfg = (strategy.pipeline_configs if strategy is not None else
               {"accumulate_steps": 1, "micro_batch_size": 1})
        self.accumulate_steps = cfg.get("accumulate_steps", 1)
        self.micro_batch_size = cfg.get("micro_batch_size", 1)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Reference pipeline_parallel.py:98. data = [inputs, labels].
        Splits into micro-batches, forward+backward each (grad accumulation
        ≡ the 1F1B result), then one optimizer step."""
        inputs, labels = data
        total = inputs.shape[0]
        micro = max(1, self.micro_batch_size)
        if total % micro != 0:
            raise InvalidArgumentError(
                f"batch size {total} must be divisible by "
                f"micro_batch_size {micro} (the reference asserts the "
                f"same in pipeline_parallel.py)")
        n_micro = total // micro
        loss_fn = self._layers._loss_fn
        if loss_fn is None:
            raise InvalidArgumentError(
                "PipelineLayer needs loss_fn for train_batch")
        total_loss = None
        for i in range(n_micro):
            lo, hi = i * micro, (i + 1) * micro
            x = inputs[lo:hi]
            y = labels[lo:hi]
            out = self._layers(x)
            loss = loss_fn(out, y)
            scaled = loss / float(n_micro)
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            total_loss = loss if total_loss is None else total_loss + loss
        if scaler is not None:
            # GradScaler.step() already advances the loss-scale state.
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total_loss / float(n_micro)

    def eval_batch(self, data, compute_loss: bool = True):
        inputs, labels = data
        out = self._layers(inputs)
        if compute_loss and self._layers._loss_fn is not None:
            return self._layers._loss_fn(out, labels)
        return out
