"""HybridParallelOptimizer.

Analog of the reference's dygraph hybrid optimizer
(python/paddle/distributed/fleet/meta_optimizers/dygraph_optimizer/
hybrid_parallel_optimizer.py): wraps the inner optimizer so gradients are
synchronized over the correct groups before the update (dp grads allreduced;
mp-duplicated grads allreduced over mp for non-distributed params; sharded
params updated locally).

TPU-native: under pjit the grad psum is already in the compiled graph (the
DataParallel hook / GSPMD derivation), so step() is mostly a passthrough;
the wrapper's real work is (a) eager-mode fallback sync, (b) ZeRO state
sharding metadata for the train-step builder.
"""

from __future__ import annotations

from typing import Optional

from ...core.tensor import Tensor
from .. import env
from ..collective import all_reduce, ReduceOp

__all__ = ["HybridParallelOptimizer", "HybridParallelGradScaler"]


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        self._sharding_enabled = bool(
            strategy is not None and
            (strategy.sharding or
             hcg.get_sharding_parallel_world_size() > 1))

    @property
    def inner_opt(self):
        return self._inner_opt

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        # dp grad sync for eager multi-rank runs happens in DataParallel
        # hooks; mp-replicated (non-distributed) params need an mp-axis
        # grad sync so replicas stay identical (reference
        # hybrid_parallel_optimizer.py _dygraph_clip + fused_allreduce_gradients)
        axis = env.current_spmd_axis("mp")
        if axis is not None:
            for p in getattr(self._inner_opt, "_parameter_list", []) or []:
                if p.grad is not None and not getattr(
                        p, "is_distributed", False):
                    all_reduce(p.grad, op=ReduceOp.AVG,
                               group=self._hcg.get_model_parallel_group())
        return self._inner_opt.step()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        return self._inner_opt.minimize(loss, startup_program, parameters,
                                        no_grad_set)

    def clear_grad(self, set_to_zero: bool = False):
        return self._inner_opt.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, state):
        return self._inner_opt.set_state_dict(state)


class HybridParallelGradScaler:
    """Wraps amp.GradScaler: found_inf must be any-reduced across the model
    parallel group so every rank makes the same skip/update decision
    (reference hybrid_parallel_gradscaler.py)."""

    def __init__(self, scaler, hcg):
        self._scaler = scaler
        self._hcg = hcg

    def _sync_found_inf(self):
        # under pjit the finite-check runs on replicated grads so ranks
        # already agree; the any-reduce matters on the explicit-SPMD path
        axis = env.current_spmd_axis("mp")
        if axis is not None:
            from ...core.tensor import to_tensor
            flag = to_tensor(1.0 if self._scaler._found_inf else 0.0)
            all_reduce(flag, op=ReduceOp.MAX,
                       group=self._hcg.get_model_parallel_group())
            self._scaler._found_inf = bool(float(flag.numpy()) > 0)

    def unscale_(self, optimizer):
        out = self._scaler.unscale_(optimizer)
        self._sync_found_inf()
        return out

    def step(self, optimizer):
        # GradScaler.step unscales internally; re-sync before the skip
        # decision by unscaling first ourselves
        self._scaler.unscale_(optimizer)
        self._sync_found_inf()
        return self._scaler.step(optimizer)

    def __getattr__(self, item):
        return getattr(self._scaler, item)
