"""Tensor-(model-)parallel layers.

Analog of the reference's Megatron-style layers
(python/paddle/distributed/fleet/meta_parallel/parallel_layers/mp_layers.py:
VocabParallelEmbedding:29, ColumnParallelLinear:85, RowParallelLinear:143).

TPU-native dual path, one code base:

* **pjit/GSPMD path (primary).** The layer holds the FULL parameter tagged
  with per-dim mesh-axis names (``Parameter.sharding_axes``); when the train
  step is jitted over the mesh (see distributed.sharding_specs), XLA shards
  the weight over the ``mp`` axis and inserts exactly the f/g collectives
  Megatron prescribes. The forward below is the plain dense math.

* **shard_map path (explicit SPMD, reference semantics).** Under
  ``shard_map`` with ``spmd_axes(mp=...)`` bound, parameters arrive as local
  shards and the ``_c_identity``/``_mp_allreduce``/``_c_concat`` calls below
  become real axis collectives — bit-for-bit the reference's comm pattern.
  Outside any SPMD trace these helpers are identity, so the same layers run
  unchanged on one chip.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ...autograd.engine import apply
from ...core.tensor import Tensor
from ...nn import functional as F
from ...nn.initializer import XavierUniform
from ...nn.layer_base import Layer
from .. import env
from ..collective import _c_concat, _c_identity, _c_split, _mp_allreduce
from ..topology import get_hybrid_communicate_group

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy"]


def _mp_degree() -> int:
    hcg = get_hybrid_communicate_group()
    return hcg.get_model_parallel_world_size() if hcg else 1


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim split over mp (reference mp_layers.py:29).
    Out-of-range ids on each shard contribute zeros; psum combines."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 weight_attr=None, mp_group=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=XavierUniform())
        self.weight.sharding_axes = ("mp", None)
        self.weight.is_distributed = True

    def forward(self, x):
        axis = env.current_spmd_axis("mp")

        def f(ids, w):
            if axis is not None and isinstance(w, jax.core.Tracer):
                # explicit-SPMD: w is the local vocab shard
                n = lax.axis_size(axis)
                per = w.shape[0]
                start = lax.axis_index(axis) * per
                local = ids - start
                ok = (local >= 0) & (local < per)
                safe = jnp.clip(local, 0, per - 1)
                out = jnp.where(ok[..., None], w[safe], 0.0)
                return lax.psum(out, axis)
            return w[ids]

        return apply("vocab_parallel_embedding", f,
                     (x, self.weight))


class ColumnParallelLinear(Layer):
    """Linear with output dim split over mp (reference mp_layers.py:85).
    fwd: identity(x) @ W_col [+ gather]; bwd: psum(dx)."""

    def __init__(self, in_features: int, out_features: int,
                 weight_attr=None, has_bias: bool = True,
                 gather_output: bool = True, mp_group=None,
                 fuse_matmul_bias: bool = False, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform())
        self.weight.sharding_axes = (None, "mp")
        self.weight.is_distributed = True
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias.sharding_axes = ("mp",)
            self.bias.is_distributed = True
        else:
            self.bias = None

    def forward(self, x):
        x = _c_identity(x)
        y = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            y = _c_concat(y)
        return y


class RowParallelLinear(Layer):
    """Linear with input dim split over mp (reference mp_layers.py:143).
    fwd: x_shard @ W_row → psum; bwd: identity(dx)."""

    def __init__(self, in_features: int, out_features: int,
                 weight_attr=None, has_bias: bool = True,
                 input_is_parallel: bool = False, mp_group=None,
                 fuse_matmul_bias: bool = False, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform())
        self.weight.sharding_axes = ("mp", None)
        self.weight.is_distributed = True
        if has_bias:
            # bias is replicated; added once after the reduce
            self.bias = self.create_parameter([out_features], is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        if not self.input_is_parallel:
            x = _c_split(x)
        y = F.linear(x, self.weight, None)
        y = _mp_allreduce(y)
        if self.bias is not None:
            y = y + self.bias
        return y


class ParallelCrossEntropy(Layer):
    """Softmax cross-entropy over mp-sharded logits (reference
    parallel_cross_entropy; vocab-parallel loss). Under explicit SPMD the
    max/sum reductions psum over mp; on the pjit path XLA derives the same
    from the logits sharding."""

    def __init__(self, mp_group=None, name=None):
        super().__init__()

    def forward(self, logits, label):
        axis = env.current_spmd_axis("mp")

        def f(lg, lb):
            if axis is not None and isinstance(lg, jax.core.Tracer):
                per = lg.shape[-1]
                start = lax.axis_index(axis) * per
                m = lax.pmax(jnp.max(lg, -1, keepdims=True), axis)
                e = jnp.exp(lg - m)
                denom = lax.psum(jnp.sum(e, -1, keepdims=True), axis)
                logp = lg - m - jnp.log(denom)
                local = lb - start
                ok = (local >= 0) & (local < per)
                safe = jnp.clip(local, 0, per - 1)
                picked = jnp.take_along_axis(
                    logp, safe[..., None], axis=-1)[..., 0]
                nll = -jnp.where(ok, picked, 0.0)
                return lax.psum(nll, axis)[..., None]
            m = jnp.max(lg, -1, keepdims=True)
            logp = lg - m - jnp.log(jnp.sum(jnp.exp(lg - m), -1,
                                            keepdims=True))
            picked = jnp.take_along_axis(logp, lb[..., None], axis=-1)
            return -picked

        return apply("parallel_cross_entropy", f, (logits, label))
