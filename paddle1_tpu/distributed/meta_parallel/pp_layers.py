"""Pipeline-parallel model description & stage partition.

Analog of the reference's ``PipelineLayer``/``LayerDesc``/``SegmentLayers``
(python/paddle/distributed/fleet/meta_parallel/parallel_layers/pp_layers.py:
61, SegmentLayers:22): the model is declared as a flat list of layer
descriptors, partitioned into contiguous stages balanced by parameter count,
and each rank builds only its stage.

TPU-native: under single-controller SPMD every process sees all stages; the
partition drives (a) which ``pp``-mesh-axis coordinate each stage's params
are pinned to (stage_sharding tags consumed by the in-graph 1F1B schedule in
distributed.pipeline) and (b) per-stage sub-Layer construction for the
eager/debug path.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from ...core.errors import InvalidArgumentError
from ...nn.layer_base import Layer
from ...nn.layer_norm_act import LayerList

__all__ = ["LayerDesc", "SharedLayerDesc", "SegmentLayers", "PipelineLayer"]


class LayerDesc:
    """Deferred layer constructor (reference pp_layers.py LayerDesc)."""

    def __init__(self, layer_func: Callable, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not (callable(layer_func)):
            raise InvalidArgumentError("LayerDesc needs a Layer class")

    def build_layer(self) -> Layer:
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({getattr(self.layer_func, '__name__', '?')})"


class SharedLayerDesc(LayerDesc):
    """Layer shared between stages, e.g. tied embeddings (reference
    pp_layers.py SharedLayerDesc): grads for the shared weight are
    all-reduced across the owning stages."""

    def __init__(self, key: str, layer_func: Callable, forward_func=None,
                 shared_weight_attr: str = "weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Balanced contiguous partition (reference pp_layers.py:22). Method
    'uniform' splits by count; 'parameters' balances by parameter volume."""

    def __init__(self, layers_desc: Sequence, num_parts: int,
                 method: str = "uniform"):
        self.descs = list(layers_desc)
        self.num_parts = num_parts
        self.method = method
        if len(self.descs) < num_parts:
            raise InvalidArgumentError(
                f"{len(self.descs)} layers cannot fill {num_parts} stages")

    def do_segment(self) -> List[int]:
        n = len(self.descs)
        if self.method == "uniform":
            base = n // self.num_parts
            rem = n % self.num_parts
            bounds = [0]
            for i in range(self.num_parts):
                bounds.append(bounds[-1] + base + (1 if i < rem else 0))
            return bounds
        if self.method == "parameters":
            # Balance stages by parameter volume: greedy boundary placement
            # over the prefix-sum of per-layer parameter counts.
            weights = [self._param_count(d) for d in self.descs]
            total = sum(weights) or 1
            target = total / self.num_parts
            bounds, acc = [0], 0.0
            for i, w in enumerate(weights):
                acc += w
                if (len(bounds) < self.num_parts and
                        acc >= target * len(bounds) and
                        n - (i + 1) >= self.num_parts - len(bounds)):
                    bounds.append(i + 1)
            while len(bounds) < self.num_parts:
                bounds.append(bounds[-1] + 1)
            bounds.append(n)
            return bounds
        if self.method.startswith("layer:"):
            # place boundaries at layers whose class name matches
            target = self.method.split(":", 1)[1]
            marks = [i for i, d in enumerate(self.descs)
                     if getattr(getattr(d, "layer_func", d), "__name__", "")
                     == target]
            if not marks:
                raise InvalidArgumentError(
                    f"segment method 'layer:{target}' matched no layers")
            if len(marks) % self.num_parts != 0:
                raise InvalidArgumentError(
                    f"'layer:{target}' matched {len(marks)} layers, not "
                    f"divisible into {self.num_parts} stages (the "
                    f"reference SegmentLayers asserts the same)")
            per = len(marks) // self.num_parts
            bounds = [0]
            for i in range(1, self.num_parts):
                bounds.append(marks[i * per])
            bounds.append(n)
            return bounds
        raise InvalidArgumentError(f"Unknown segment method {self.method}")

    _count_cache: dict = {}

    @classmethod
    def _param_count(cls, desc) -> int:
        if isinstance(desc, Layer):
            return sum(int(np.prod(p.shape)) for p in desc.parameters()) or 1
        if isinstance(desc, LayerDesc):
            # Measuring requires building; cache per constructor signature
            # so homogeneous stacks (N identical blocks) build ONE sample
            # layer, not N — the built sample is dropped immediately.
            key = (desc.layer_func, repr(desc.inputs), repr(desc.kwargs))
            if key not in cls._count_cache:
                try:
                    built = desc.build_layer()
                    cls._count_cache[key] = sum(
                        int(np.prod(p.shape))
                        for p in built.parameters()) or 1
                except Exception:
                    cls._count_cache[key] = 1
            return cls._count_cache[key]
        return 1


class PipelineLayer(Layer):
    """The stage-partitioned model (reference pp_layers.py:61).

    ``forward`` runs ALL stages sequentially (correct math everywhere; on a
    pod the in-graph 1F1B schedule in distributed.pipeline consumes
    ``stage_descs()`` instead). Parameters of stage s are tagged with
    ``pp_stage = s`` so the pipeline runner can pin them to the pp-axis
    coordinate.
    """

    def __init__(self, layers: Sequence, num_stages: Optional[int] = None,
                 topology=None, loss_fn=None, seg_method: str = "uniform",
                 recompute_interval: int = 0, **kwargs):
        super().__init__()
        from ..topology import get_hybrid_communicate_group
        self._loss_fn = loss_fn
        hcg = get_hybrid_communicate_group()
        self._num_stages = (num_stages if num_stages is not None else
                            (hcg.get_pipe_parallel_world_size() if hcg
                             else 1))
        self._descs = list(layers)
        seg = SegmentLayers(self._descs, self._num_stages, seg_method)
        self.segment_parts = seg.do_segment()
        self.recompute_interval = recompute_interval

        self._shared_layers = {}
        built: List[Layer] = []
        self._stage_of_layer: List[int] = []
        for stage in range(self._num_stages):
            lo, hi = self.segment_parts[stage], self.segment_parts[stage + 1]
            for i in range(lo, hi):
                d = self._descs[i]
                if isinstance(d, SharedLayerDesc):
                    if d.layer_name not in self._shared_layers:
                        self._shared_layers[d.layer_name] = d.build_layer()
                    lyr = self._shared_layers[d.layer_name]
                elif isinstance(d, LayerDesc):
                    lyr = d.build_layer()
                elif isinstance(d, Layer):
                    lyr = d
                elif callable(d):
                    lyr = _FnLayer(d)
                else:
                    raise InvalidArgumentError(f"Bad pipeline desc: {d!r}")
                built.append(lyr)
                self._stage_of_layer.append(stage)
                for p in lyr.parameters():
                    p.pp_stage = stage
        self.run_function = LayerList(built)

    def get_stage_from_index(self, idx: int) -> int:
        return self._stage_of_layer[idx]

    def stage_layers(self, stage: int) -> List[Layer]:
        return [l for l, s in zip(self.run_function, self._stage_of_layer)
                if s == stage]

    @property
    def num_stages(self) -> int:
        return self._num_stages

    def forward(self, x):
        from ..fleet.utils.recompute import recompute
        for i, lyr in enumerate(self.run_function):
            if (self.recompute_interval > 0 and
                    i % self.recompute_interval == 0 and self.training):
                x = recompute(lyr, *x) if isinstance(x, tuple) \
                    else recompute(lyr, x)
            else:
                x = lyr(*x) if isinstance(x, tuple) else lyr(x)
        return x


class _FnLayer(Layer):
    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, *args):
        return self._fn(*args)
