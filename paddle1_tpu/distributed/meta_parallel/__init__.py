"""Hybrid-parallel building blocks (reference
python/paddle/distributed/fleet/meta_parallel/)."""

from .mp_layers import (ColumnParallelLinear, ParallelCrossEntropy,
                        RowParallelLinear, VocabParallelEmbedding)
from .pp_layers import (LayerDesc, PipelineLayer, SegmentLayers,
                        SharedLayerDesc)
from .model_parallel import ModelParallel
from .pipeline_parallel import PipelineParallel
from .hybrid_optimizer import (HybridParallelGradScaler,
                               HybridParallelOptimizer)
from .random import (get_rng_state_tracker, model_parallel_random_seed,
                     RNGStatesTracker)

__all__ = ["ColumnParallelLinear", "RowParallelLinear",
           "VocabParallelEmbedding", "ParallelCrossEntropy", "LayerDesc",
           "SharedLayerDesc", "PipelineLayer", "SegmentLayers",
           "ModelParallel", "PipelineParallel", "HybridParallelOptimizer",
           "HybridParallelGradScaler", "get_rng_state_tracker",
           "model_parallel_random_seed", "RNGStatesTracker"]
