"""ModelParallel wrapper (reference
python/paddle/distributed/fleet/meta_parallel/model_parallel.py:25: wraps a
dygraph model for TP — broadcasts params/inputs within the mp group at init).

On TPU, replication-vs-sharding of each parameter is a compile-time sharding
spec, so the init-time broadcast disappears; the wrapper's remaining job is
dp-grad sync (inherited DataParallel semantics across the dp axis) while mp
collectives live inside the mp_layers themselves.
"""

from __future__ import annotations

from ...nn.layer_base import Layer
from ..parallel import DataParallel

__all__ = ["ModelParallel"]


class ModelParallel(DataParallel):
    def __init__(self, layers: Layer, hcg, strategy=None, **kwargs):
        super().__init__(layers,
                         group=hcg.get_data_parallel_group())
        self._hcg = hcg
