"""Tensor-parallel RNG wiring (reference
python/paddle/distributed/fleet/meta_parallel/parallel_layers/
random.py:23-77).

Two kinds of randomness coexist under tensor parallelism: ops on
REPLICATED tensors (weight init, dropout before the split) must draw
identical values on every mp rank, while dropout on mp-SHARDED
activations must draw a distinct mask per rank — otherwise the "random"
mask is correlated across the hidden dimension. The tracker provides
both: the default stream is seeded identically everywhere
(``paddle.seed(global)``), and the ``model_parallel_rng`` tracked
stream is seeded per-rank; wrap sharded-region dropout in
``get_rng_state_tracker().rng_state()`` exactly as in the reference
(e.g. inside the ColumnParallel->dropout->RowParallel MLP block).

TPU note: under jit tracing the tracked stream stays functional — the
per-name subkey is folded from the ``rng_scope`` key, so the compiled
step is deterministic in its key argument on every rank while still
decorrelated across ranks.
"""

from __future__ import annotations

from ...core.generator import (MODEL_PARALLEL_RNG, RNGStatesTracker,
                               get_rng_tracker, seed as _seed_all)

__all__ = ["get_rng_state_tracker", "model_parallel_random_seed",
           "RNGStatesTracker", "MODEL_PARALLEL_RNG"]


def get_rng_state_tracker() -> RNGStatesTracker:
    """The reference's spelling for the global tracker."""
    return get_rng_tracker()


def model_parallel_random_seed(seed: int = 2048) -> None:
    """Seed the replicated stream with ``seed`` and register the
    per-rank ``model_parallel_rng`` stream at ``seed + 1024 + mp_rank``
    (reference random.py:66)."""
    from ..topology import get_hybrid_communicate_group
    hcg = get_hybrid_communicate_group()
    rank = hcg.get_model_parallel_rank() if hcg is not None else 0
    local_seed = seed + 1024 + rank
    _seed_all(seed)  # also resets the tracker
    get_rng_tracker().add(MODEL_PARALLEL_RNG, local_seed)
