"""Sharded embedding engine: the LFU/TTL admission–eviction bridge
between the HBM tier and the host/remote table tiers (ISSUE 19
tentpole; reference heter_ps/heter_comm.h HeterComm + ps_gpu_wrapper's
build/pull/push pass structure).

The reference trains "trillions of parameters" by keeping the *hot*
working set of embedding rows in accelerator memory and the long tail
on host RAM / remote parameter servers. The TPU-native analog:

* the hot tier is an :class:`~paddle1_tpu.distributed.hbm_embedding.
  HBMShardedEmbedding` — a fixed-capacity row-sharded device table
  trained in-graph at one dispatch per step;
* this engine owns the **logical id → HBM slot** mapping. ``route()``
  is called on the input pipeline (host side, outside the jitted
  step): it admits misses by *moving* the row (plus optimizer slots
  and adam step counts) out of the host tier (``EmbeddingService``,
  whose shards may be remote ``TableServer`` clients — the cluster
  tier), and demotes LFU/TTL victims back down the same way. A row
  therefore lives in **exactly one tier at a time** — the
  exactly-once accounting the bench gate asserts
  (``admit_total - demote_total == resident``);
* occupancy is a first-class sensor: the engine registers with the
  PR 13 HBM census under the ``embed`` subsystem (logical occupancy —
  resident rows × row bytes; the fixed weight *allocation* stays
  attributed to ``params`` by the ParallelEngine registration) and
  publishes the ``embed_*`` gauge/counter families;
* ``drain_dirty()`` yields the per-step changed rows for the
  online-learning delta path (``embedding_delta.DeltaLog``).

Binding: by default the engine reads/writes rows through the layer's
``rows``/``write_rows`` (eager tests, serving). After constructing a
:class:`~paddle1_tpu.distributed.parallel_engine.ParallelEngine`, call
:meth:`bind_engine` — the live rows then move into the engine's
``params``/``opt_state`` buffers (which ride the jitted step as
arguments, so host-side admission writes never retrace).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Set

import numpy as np

from ..core.errors import PreconditionNotMetError

__all__ = ["ShardedEmbeddingEngine"]


class _Occupancy:
    """A census leaf whose ``nbytes`` is the engine's LOGICAL HBM
    occupancy (resident rows × row bytes). The weight's fixed
    allocation belongs to ``params``; this reports what the admission
    controller is actually using of it."""

    def __init__(self, engine: "ShardedEmbeddingEngine"):
        self._e = engine

    @property
    def nbytes(self) -> int:
        return self._e.resident_rows * self._e.row_bytes


class ShardedEmbeddingEngine:
    """Admission/eviction controller over (HBM layer, host service).

    Parameters
    ----------
    hbm : HBMShardedEmbedding — the hot tier (capacity =
        ``hbm.vocab_size`` slots).
    host : EmbeddingService — the capacity tier (its shards may be
        RemoteTables — then demotion crosses the wire to the cluster
        tier).
    hbm_row_budget : admission ceiling in rows (≤ capacity; default =
        capacity). The bench gate holds census occupancy to this.
    ttl_s : seconds of idleness after which a resident row demotes on
        the next ``route``/``sweep_ttl`` (None = LFU pressure only).
    metrics : optional obs registry for the ``embed_*`` families.
    """

    def __init__(self, hbm, host, hbm_row_budget: Optional[int] = None,
                 ttl_s: Optional[float] = None, metrics=None):
        self.hbm = hbm
        self.host = host
        cap = int(hbm.vocab_size)
        self.capacity = cap
        self.budget = cap if hbm_row_budget is None \
            else min(int(hbm_row_budget), cap)
        if self.budget < 1:
            raise ValueError("hbm_row_budget must be >= 1")
        if getattr(host, "dim", None) is not None and \
                int(host.dim) != int(hbm.embedding_dim):
            raise ValueError(
                f"host tier dim={host.dim} != HBM tier dim="
                f"{hbm.embedding_dim} — the tiers disagree on row width")
        self.dim = int(hbm.embedding_dim)
        self.ttl_s = None if ttl_s is None else float(ttl_s)
        self.metrics = metrics
        self._lock = threading.RLock()
        self._slot_of: Dict[int, int] = {}   # logical id -> slot
        self._id_of: Dict[int, int] = {}     # slot -> logical id
        self._free: List[int] = list(range(cap - 1, -1, -1))
        self._freq: Dict[int, int] = {}      # LFU occurrence counts
        self._touch: Dict[int, float] = {}   # last-route monotonic time
        self._steps: Dict[int, int] = {}     # adam per-row step counts
        self._dirty: Set[int] = set()        # trained since last drain
        self._ever: Set[int] = set()         # every id ever admitted
        self.admit_total = 0
        self.demote_total = 0
        self.ttl_evict_total = 0
        self.hit_total = 0
        self.miss_total = 0
        self._peng = None
        self._pkey: Optional[str] = None
        self._occ = _Occupancy(self)
        from ..obs import hbm as obs_hbm
        obs_hbm.register("embed", self, lambda e: e._occ,
                         name="ShardedEmbeddingEngine.occupancy")

    # -- row/slot storage accessors -----------------------------------------

    def bind_engine(self, parallel_engine, model=None) -> str:
        """Route row/slot reads+writes through a ParallelEngine's live
        ``params``/``opt_state`` buffers (the layer's own weight is a
        stale copy while the engine trains). Returns the param key."""
        model = model if model is not None else parallel_engine.model
        key = None
        for k, t in model.state_dict().items():
            if t is self.hbm.weight:
                key = k
                break
        if key is None or key not in parallel_engine.params:
            raise PreconditionNotMetError(
                "bind_engine: the HBM embedding's weight is not among "
                "the ParallelEngine's params — bind the engine that "
                "trains this model")
        with self._lock:
            self._peng, self._pkey = parallel_engine, key
        return key

    def _weight(self):
        if self._peng is not None:
            return self._peng.params[self._pkey]
        return self.hbm.weight.data

    def _set_weight(self, arr) -> None:
        import jax
        if self._peng is not None:
            # preserve the param's sharding: .at[].set may produce a
            # differently-placed result, and the jitted step expects
            # the registered spec
            old = self._peng.params[self._pkey]
            sh = getattr(old, "sharding", None)
            self._peng.params[self._pkey] = \
                jax.device_put(arr, sh) if sh is not None else arr
        else:
            self.hbm.weight._data = arr

    def _slot_arrays(self) -> Dict[str, object]:
        """Device-side optimizer slot arrays for the bound param
        ([capacity, dim] each; empty dict unbound or sgd)."""
        if self._peng is None:
            return {}
        slots = self._peng.opt_state[0].get(self._pkey, {})
        return {n: a for n, a in slots.items()
                if np.ndim(a) == 2 and a.shape[0] == self.capacity}

    def _set_slot_array(self, name: str, arr) -> None:
        import jax
        old = self._peng.opt_state[0][self._pkey][name]
        sh = getattr(old, "sharding", None)
        self._peng.opt_state[0][self._pkey][name] = \
            jax.device_put(arr, sh) if sh is not None else arr

    def read_rows(self, slots: np.ndarray) -> np.ndarray:
        import jax
        return np.asarray(jax.device_get(self._weight()))[slots]

    def write_rows(self, slots: np.ndarray, rows: np.ndarray) -> None:
        import jax.numpy as jnp
        w = self._weight()
        vals = jnp.asarray(np.asarray(rows, np.float32), dtype=w.dtype)
        self._set_weight(w.at[jnp.asarray(slots)].set(vals))

    # -- introspection -------------------------------------------------------

    @property
    def resident_rows(self) -> int:
        return len(self._slot_of)

    @property
    def row_bytes(self) -> int:
        w = self._weight()
        itemsize = getattr(w, "dtype", np.dtype(np.float32)).itemsize
        return self.dim * int(itemsize)

    def resident_ids(self) -> np.ndarray:
        with self._lock:
            return np.asarray(sorted(self._slot_of), np.int64)

    def slot_of(self, logical_id: int) -> Optional[int]:
        return self._slot_of.get(int(logical_id))

    def tier_of(self, logical_id: int) -> str:
        """'hbm' | 'host' | 'absent' — a row is in exactly one tier."""
        i = int(logical_id)
        with self._lock:
            if i in self._slot_of:
                return "hbm"
        for sh in self.host.shards:
            has = getattr(sh, "has", None)
            if has is not None and bool(has([i])[0]):
                return "host"
        return "absent"

    def accounting(self) -> dict:
        """The exactly-once ledger the bench gate asserts: every
        admission is matched by residency or exactly one demotion."""
        with self._lock:
            return {"resident": len(self._slot_of),
                    "admit_total": self.admit_total,
                    "demote_total": self.demote_total,
                    "ttl_evict_total": self.ttl_evict_total,
                    "hit_total": self.hit_total,
                    "miss_total": self.miss_total,
                    "balanced": (self.admit_total - self.demote_total
                                 == len(self._slot_of))}

    # -- the tier bridge -----------------------------------------------------

    def route(self, ids, now: Optional[float] = None) -> np.ndarray:
        """Map logical feature ids → HBM slot indices, admitting misses
        from the host tier (pull-on-miss promotion) and demoting LFU/TTL
        victims to stay under ``budget``. Call from the input pipeline,
        outside the jitted step; feed the returned slots to the model.
        Never evicts an id needed by the current batch."""
        ids_np = np.asarray(ids, np.int64)
        flat = ids_np.reshape(-1)
        with self._lock:
            t = time.monotonic() if now is None else float(now)
            uniq, counts = np.unique(flat, return_counts=True)
            pinned = set(int(i) for i in uniq)
            if len(pinned) > self.budget:
                raise PreconditionNotMetError(
                    f"batch needs {len(pinned)} unique rows but "
                    f"hbm_row_budget={self.budget} — raise the budget "
                    "or shrink the batch's id fan-out")
            if self.ttl_s is not None:
                self._sweep_ttl_locked(t, keep=pinned)
            missing = [int(i) for i in uniq if int(i) not in
                       self._slot_of]
            hits = len(pinned) - len(missing)
            self.hit_total += hits
            self.miss_total += len(missing)
            # make room: stay under budget AND have a free slot per miss
            need = max(len(self._slot_of) + len(missing) - self.budget,
                       len(missing) - len(self._free))
            if need > 0:
                victims = self._pick_victims(need, keep=pinned)
                self._demote_locked(victims)
            if missing:
                self._admit_locked(missing)
            for i, c in zip(uniq, counts):
                i = int(i)
                self._freq[i] = self._freq.get(i, 0) + int(c)
                self._touch[i] = t
            self._dirty.update(pinned)
            if self.metrics is not None and (hits or missing):
                if hits:
                    self.metrics.counter("embed_hit_total").inc(hits)
                if missing:
                    self.metrics.counter("embed_miss_total").inc(
                        len(missing))
            lut = self._slot_of
            return np.asarray([lut[int(i)] for i in flat],
                              np.int64).reshape(ids_np.shape)

    def _pick_victims(self, n: int, keep: Set[int]) -> List[int]:
        cands = [i for i in self._slot_of if i not in keep]
        if len(cands) < n:
            raise PreconditionNotMetError(
                f"cannot demote {n} rows: only {len(cands)} resident "
                f"rows are not pinned by the current batch (budget="
                f"{self.budget}, capacity={self.capacity})")
        # LFU with LRU tiebreak — the reference cache's victim policy.
        # The final id tiebreak keeps victim choice independent of dict
        # insertion order, so a restore (which rebuilds the mapping
        # sorted) replays the exact eviction sequence of the
        # uninterrupted run
        cands.sort(key=lambda i: (self._freq.get(i, 0),
                                  self._touch.get(i, 0.0), i))
        return cands[:n]

    def _admit_locked(self, ids: List[int]) -> None:
        """Promote ids out of the host tier (move semantics: the host
        copy is removed) into freshly assigned slots."""
        got = self.host.evict(ids, create=True)
        # host returns them in our order (create=True → all present)
        slots = [self._free.pop() for _ in ids]
        for i, s, st in zip(ids, slots, got["steps"]):
            self._slot_of[i] = s
            self._id_of[s] = i
            self._steps[i] = int(st)
            self._ever.add(i)
        slots_np = np.asarray(slots, np.int64)
        self.write_rows(slots_np, got["rows"])
        dev_slots = self._slot_arrays()
        if dev_slots and got["slots"].shape[1]:
            import jax.numpy as jnp
            idx = jnp.asarray(slots_np)
            for j, name in enumerate(sorted(dev_slots)):
                if j >= got["slots"].shape[1]:
                    break
                arr = self._peng.opt_state[0][self._pkey][name]
                vals = jnp.asarray(got["slots"][:, j, :],
                                   dtype=arr.dtype)
                self._set_slot_array(name, arr.at[idx].set(vals))
        self.admit_total += len(ids)
        if self.metrics is not None:
            self.metrics.counter("embed_admit_total").inc(len(ids))

    def _demote_locked(self, ids: List[int], ttl: bool = False) -> None:
        """Move resident rows (values + optimizer slots + step counts)
        down to the host tier and free their slots."""
        if not ids:
            return
        slots_np = np.asarray([self._slot_of[i] for i in ids], np.int64)
        rows = self.read_rows(slots_np)
        dev_slots = self._slot_arrays()
        if dev_slots:
            import jax
            stacked = [np.asarray(jax.device_get(
                dev_slots[name]))[slots_np]
                for name in sorted(dev_slots)]
            slot_block = np.stack(stacked, axis=1)   # [n, n_slots, dim]
        else:
            slot_block = np.zeros((len(ids), 0, self.dim), np.float32)
        steps = np.asarray([self._steps.get(i, 0) for i in ids],
                           np.int64)
        self.host.admit(np.asarray(ids, np.int64), rows, slot_block,
                        steps)
        for i in ids:
            s = self._slot_of.pop(i)
            self._id_of.pop(s, None)
            self._free.append(s)
            self._steps.pop(i, None)
        self.demote_total += len(ids)
        if ttl:
            self.ttl_evict_total += len(ids)
        if self.metrics is not None:
            self.metrics.counter("embed_demote_total").inc(len(ids))
            if ttl:
                self.metrics.counter("embed_ttl_evict_total").inc(
                    len(ids))

    def _sweep_ttl_locked(self, now: float, keep: Set[int]) -> None:
        expired = [i for i, t in self._touch.items()
                   if i in self._slot_of and i not in keep
                   and now - t > self.ttl_s]
        self._demote_locked(expired, ttl=True)

    def sweep_ttl(self, now: Optional[float] = None) -> int:
        """Demote every TTL-expired resident row now (the idle-time
        sweep); returns how many moved."""
        if self.ttl_s is None:
            return 0
        with self._lock:
            before = self.demote_total
            self._sweep_ttl_locked(
                time.monotonic() if now is None else float(now), set())
            return self.demote_total - before

    def demote_all(self) -> int:
        """Flush every resident row to the host tier (checkpoint /
        shutdown barrier). Returns how many moved."""
        with self._lock:
            ids = list(self._slot_of)
            self._demote_locked(ids)
            return len(ids)

    # -- online-learning delta feed -----------------------------------------

    def drain_dirty(self):
        """(ids, rows) for every logical id trained since the last
        drain — resident rows read from the device, already-demoted
        rows from the host tier — the trainer side of the delta-publish
        loop. Clears the dirty set."""
        with self._lock:
            dirty, self._dirty = sorted(self._dirty), set()
            res = [i for i in dirty if i in self._slot_of]
            cold = [i for i in dirty if i not in self._slot_of]
            rows = np.zeros((len(dirty), self.dim), np.float32)
            order = {i: k for k, i in enumerate(dirty)}
            if res:
                got = self.read_rows(np.asarray(
                    [self._slot_of[i] for i in res], np.int64))
                for i, r in zip(res, got):
                    rows[order[i]] = r
        if cold:
            got = self.host.pull(np.asarray(cold, np.int64))
            for i, r in zip(cold, got):
                rows[order[i]] = r
        return np.asarray(dirty, np.int64), rows

    # -- observability -------------------------------------------------------

    def publish_gauges(self, m=None) -> None:
        m = m if m is not None else self.metrics
        if m is None:
            return
        with self._lock:
            resident = len(self._slot_of)
        m.gauge("embed_hbm_rows").set(resident)
        m.gauge("embed_hbm_budget_rows").set(self.budget)
        m.gauge("embed_hbm_bytes").set(resident * self.row_bytes)
        m.gauge("embed_host_rows").set(len(self.host))

    # -- persistence (PR 2 manifest-friendly: arrays only) ------------------

    def state_dict(self) -> dict:
        with self._lock:
            ids = sorted(self._slot_of)
            return {
                "ids": np.asarray(ids, np.int64),
                "slots": np.asarray([self._slot_of[i] for i in ids],
                                    np.int64),
                "freq_ids": np.asarray(sorted(self._freq), np.int64),
                "freq": np.asarray([self._freq[i]
                                    for i in sorted(self._freq)],
                                   np.int64),
                "steps": np.asarray([self._steps.get(i, 0)
                                     for i in ids], np.int64),
                "counters": np.asarray(
                    [self.admit_total, self.demote_total,
                     self.ttl_evict_total, self.hit_total,
                     self.miss_total], np.int64),
                "dirty": np.asarray(sorted(self._dirty), np.int64),
                # the free list ORDER and the last-route times are part
                # of placement determinism: slot assignment pops the
                # free list, LRU tiebreak reads _touch — both must
                # replay bit-identically after a restore
                "free": np.asarray(self._free, np.int64),
                "touch_ids": np.asarray(sorted(self._touch), np.int64),
                "touch": np.asarray([self._touch[i]
                                     for i in sorted(self._touch)],
                                    np.float64),
            }

    def load_state_dict(self, state: dict) -> None:
        with self._lock:
            ids = np.asarray(state["ids"], np.int64)
            slots = np.asarray(state["slots"], np.int64)
            self._slot_of = {int(i): int(s) for i, s in zip(ids, slots)}
            self._id_of = {int(s): int(i) for i, s in zip(ids, slots)}
            used = set(int(s) for s in slots)
            if "free" in state:
                self._free = [int(s)
                              for s in np.asarray(state["free"],
                                                  np.int64)]
            else:  # pre-sidecar checkpoint: order is lost
                self._free = [s for s in range(self.capacity - 1, -1, -1)
                              if s not in used]
            self._freq = {int(i): int(f) for i, f in zip(
                np.asarray(state["freq_ids"], np.int64),
                np.asarray(state["freq"], np.int64))}
            self._steps = {int(i): int(t) for i, t in zip(
                ids, np.asarray(state["steps"], np.int64))}
            if "touch_ids" in state:
                self._touch = {int(i): float(x) for i, x in zip(
                    np.asarray(state["touch_ids"], np.int64),
                    np.asarray(state["touch"], np.float64))}
            else:
                self._touch = {int(i): 0.0 for i in ids}
            self._ever = set(self._slot_of) | set(self._freq)
            (self.admit_total, self.demote_total, self.ttl_evict_total,
             self.hit_total, self.miss_total) = [
                int(x) for x in np.asarray(state["counters"], np.int64)]
            self._dirty = set(
                int(i) for i in np.asarray(state.get("dirty", []),
                                           np.int64))
