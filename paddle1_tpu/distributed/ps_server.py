"""Network transport for the embedding-table service — the scoped analog
of the reference's brpc parameter-server processes.

The reference runs dedicated PS processes (BrpcPsServer,
/root/reference/paddle/fluid/distributed/service/brpc_ps_server.cc) that
workers dial for pull_sparse/push_sparse
(brpc_ps_client.cc). Here the same split: :class:`TableServer` hosts
:class:`~paddle1_tpu.distributed.ps.SparseTable` shards behind a TCP
socket; :class:`RemoteTable` is a client with the exact pull/push
interface of a local table, so :class:`EmbeddingService` routes to local
and remote shards identically.

Protocol: length-prefixed pickled (op, payload) tuples over TCP, one
request per round-trip, thread-per-connection on the server. Pickle is
acceptable for the same reason the reference's brpc endpoints are: the
PS protocol runs inside a trusted training cluster, never on a public
interface — bind to cluster-internal addresses only. Defense-in-depth:
set ``PADDLE_PS_SECRET`` (any string, same value on every node) and each
frame carries an HMAC-SHA256 tag that is verified BEFORE the payload is
unpickled, so a stray client that can reach the port but lacks the
secret cannot reach the deserializer.

Env contract (reference launch_utils.py PS mode):
``PADDLE_PSERVERS_IP_PORT_LIST`` = comma-separated ``host:port`` of the
table servers; ``TRAINING_ROLE`` = ``PSERVER`` | ``TRAINER``;
``PADDLE_PORT`` = this server's port. ``fleet.init_server/run_server``
consume these (fleet_base.py).
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import os
import pickle
import socket
import socketserver
import struct
import threading
from typing import Optional, Sequence

import numpy as np

from ..core.errors import PreconditionNotMetError
from .ps import SparseTable

__all__ = ["TableServer", "RemoteTable", "remote_service"]

_HDR = struct.Struct("!BI")  # (tag-present flag, payload length)
_MAX_MSG = 1 << 30
_TAG_LEN = hashlib.sha256().digest_size


_SMALL_MSG = 1 << 20

_log = __import__("logging").getLogger("paddle1_tpu.ps")


class _AuthError(ConnectionError):
    """Frame failed/skipped HMAC authentication (vs. a plain socket
    error): the server logs it and tells the peer why before closing."""


def _secret() -> Optional[bytes]:
    s = os.environ.get("PADDLE_PS_SECRET")
    return s.encode() if s else None


def _send(sock, obj) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    key = _secret()
    tag = _hmac.new(key, payload, hashlib.sha256).digest() if key else b""
    hdr = _HDR.pack(1 if key else 0, len(payload))
    if len(payload) < _SMALL_MSG:
        # one segment: avoids the Nagle write-write-read stall on the
        # per-step pull/push round-trips (the copy is cheap at this size)
        sock.sendall(hdr + tag + payload)
    else:
        sock.sendall(hdr + tag)
        sock.sendall(payload)  # no second copy of a big body


def _recv(sock):
    hdr = _recv_exact(sock, _HDR.size)
    if hdr is None:
        return None
    tagged, n = _HDR.unpack(hdr)
    if n > _MAX_MSG:
        raise ValueError(f"ps message too large: {n} bytes")
    key = _secret()
    tag = b""
    if tagged:
        tag = _recv_exact(sock, _TAG_LEN)
        if tag is None:
            raise ConnectionError("peer closed mid-message")
    elif key:
        # the flag makes asymmetric configuration a loud error, not a
        # mutual read-hang: without it we would consume payload bytes as
        # a tag and then block waiting for the remainder. Drain the body
        # first so an err reply can be framed on an aligned stream.
        _recv_exact(sock, n)
        raise _AuthError(
            "peer sent an unauthenticated ps frame but this side has "
            "PADDLE_PS_SECRET set — configure the same secret on every "
            "node")
    body = _recv_exact(sock, n)
    if body is None:
        raise ConnectionError("peer closed mid-message")
    if key and not _hmac.compare_digest(
            tag, _hmac.new(key, body, hashlib.sha256).digest()):
        # authenticate BEFORE deserializing: an unauthenticated client
        # never reaches pickle.loads
        raise _AuthError("ps frame failed HMAC authentication "
                         "(PADDLE_PS_SECRET mismatch)")
    return pickle.loads(body)


def _recv_exact(sock, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            if buf:
                raise ConnectionError("peer closed mid-message")
            return None  # clean EOF between messages
        buf += chunk
    return buf


class _Handler(socketserver.BaseRequestHandler):
    def setup(self):
        self.request.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def handle(self):
        table: SparseTable = self.server.table  # type: ignore[attr-defined]
        while True:
            try:
                msg = _recv(self.request)
            except _AuthError as e:
                # surface the misconfiguration on both sides: log here,
                # send the reason to the peer (the reply frame carries a
                # tag the peer simply skips if it has no secret), close
                _log.warning("dropping ps client %s: %s",
                             self.client_address, e)
                try:
                    _send(self.request, ("err", str(e)))
                except OSError:
                    pass
                return
            except (ConnectionError, OSError):
                return
            if msg is None:
                return
            op, payload = msg
            try:
                if op == "pull":
                    _send(self.request, ("ok", table.pull(payload)))
                elif op == "push":
                    ids, grads = payload
                    table.push(ids, grads)
                    _send(self.request, ("ok", None))
                elif op == "len":
                    _send(self.request, ("ok", len(table)))
                elif op == "state":
                    _send(self.request, ("ok", table.state_dict()))
                elif op == "load":
                    table.load_state_dict(payload)
                    _send(self.request, ("ok", None))
                elif op == "ping":
                    _send(self.request, ("ok", "pong"))
                elif op == "dim":
                    _send(self.request, ("ok", table.dim))
                elif op in ("call", "tcall"):
                    # whitelisted table method, never arbitrary attrs.
                    # "call" targets the primary table (GraphTable
                    # sampling etc.); "tcall" routes by table NAME
                    # (reference: one brpc PS serves many tables by id —
                    # a Downpour node pairs its sparse shard with dense
                    # blocks on one port).
                    if op == "call":
                        tname, (method, args, kwargs) = None, payload
                    else:
                        tname, method, args, kwargs = payload
                    aux = self.server.aux_tables  # type: ignore[attr-defined]
                    tgt = table if tname is None else aux.get(tname)
                    if tgt is None:
                        _send(self.request,
                              ("err", f"no table named {tname!r} on this "
                                      f"server (have {sorted(aux)})"))
                        continue
                    allowed = getattr(tgt, "RPC_METHODS", frozenset())
                    if method not in allowed:
                        _send(self.request,
                              ("err", f"method {method!r} not in "
                                      + ("this table's"
                                         if tname is None else
                                         f"table {tname!r}'s")
                                      + " RPC_METHODS"))
                    else:
                        _send(self.request,
                              ("ok", getattr(tgt, method)(*args, **kwargs)))
                elif op == "tlist":
                    _send(self.request,
                          ("ok", sorted(self.server.aux_tables)))  # type: ignore[attr-defined]
                elif op == "shutdown":
                    _send(self.request, ("ok", None))

                    def _stop(server=self.server):
                        server.shutdown()
                        server.server_close()  # release the listening fd
                    threading.Thread(target=_stop, daemon=True).start()
                    return
                else:
                    _send(self.request, ("err", f"unknown op {op!r}"))
            except Exception as e:  # keep serving other workers
                try:
                    _send(self.request, ("err", f"{type(e).__name__}: {e}"))
                except OSError:
                    return


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class TableServer:
    """Serve ONE SparseTable shard over TCP (the reference's one
    brpc_ps_server process per PS node). ``serve_forever`` blocks (use
    from ``fleet.run_server``); ``start`` runs in a background thread
    (tests, notebooks)."""

    def __init__(self, table: SparseTable, host: str = "127.0.0.1",
                 port: int = 0, aux_tables: Optional[dict] = None):
        self._srv = _TCPServer((host, port), _Handler)
        self._srv.table = table  # type: ignore[attr-defined]
        # named side tables on the same port (dense blocks beside the
        # sparse shard — the reference's multi-table PS node)
        self._srv.aux_tables = dict(aux_tables or {})  # type: ignore[attr-defined]
        self.table = table
        self.aux_tables = self._srv.aux_tables  # type: ignore[attr-defined]
        self.host, self.port = self._srv.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    def serve_forever(self):
        self._srv.serve_forever()

    def start(self) -> "TableServer":
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)


class RemoteTable:
    """Client-side twin of SparseTable: same pull/push/state interface,
    rows live in the server process (brpc_ps_client.cc pull_sparse/
    push_sparse). One persistent connection, lock-serialized (matching
    the per-table lock of the local shard)."""

    def __init__(self, endpoint: str, timeout: float = 30.0):
        host, port = endpoint.rsplit(":", 1)
        self.endpoint = endpoint
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()
        self.dim = self._call("dim")  # also validates the connection

    def _call(self, op, payload=None):
        with self._lock:
            _send(self._sock, (op, payload))
            reply = _recv(self._sock)
        if reply is None:
            raise ConnectionError(
                f"table server {self.endpoint} closed the connection")
        status, out = reply
        if status != "ok":
            raise PreconditionNotMetError(f"table server {self.endpoint}: "
                                          f"{out}")
        return out

    def pull(self, ids: Sequence[int]) -> np.ndarray:
        return self._call("pull", np.asarray(ids, np.int64))

    def push(self, ids: Sequence[int], grads) -> None:
        self._call("push", (np.asarray(ids, np.int64),
                            np.asarray(grads, np.float32)))

    # tier-bridge surface: rows + optimizer slots move across the wire
    # (SparseTable whitelists both in RPC_METHODS), so the remote
    # cluster tier composes with the HBM/host demote-promote machinery
    # exactly like a local shard

    def has(self, ids: Sequence[int]) -> np.ndarray:
        return self.call("has", np.asarray(ids, np.int64))

    def evict(self, ids: Sequence[int], create: bool = False) -> dict:
        return self.call("evict", np.asarray(ids, np.int64),
                         create=create)

    def admit(self, ids: Sequence[int], rows, slots=None,
              steps=None) -> None:
        self.call("admit", np.asarray(ids, np.int64),
                  np.asarray(rows, np.float32), slots, steps)

    def __len__(self) -> int:
        return self._call("len")

    def state_dict(self) -> dict:
        return self._call("state")

    def load_state_dict(self, state: dict) -> None:
        self._call("load", state)

    def ping(self) -> bool:
        return self._call("ping") == "pong"

    def call(self, method: str, *args, **kwargs):
        """Invoke a whitelisted table method remotely (GraphTable's
        sampling surface and other non-embedding tables)."""
        return self._call("call", (method, args, kwargs))

    def table_call(self, table_name: Optional[str], method: str, *args,
                   **kwargs):
        """Invoke a whitelisted method on a NAMED table of this server
        (dense blocks served beside the sparse shard); ``None`` targets
        the primary table."""
        return self._call("tcall", (table_name, method, args, kwargs))

    def list_tables(self):
        return self._call("tlist")

    def shutdown_server(self) -> None:
        self._call("shutdown")

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


def remote_service(dim: int, endpoints: Sequence[str]):
    """EmbeddingService whose shards are RemoteTables — one per server
    endpoint, routed by ``id % num_shards`` exactly like local shards
    (the reference's shard_num partition over PS nodes)."""
    from .ps import EmbeddingService
    return EmbeddingService(dim, shards=[RemoteTable(ep)
                                         for ep in endpoints])
