"""Network transport for the embedding-table service — the scoped analog
of the reference's brpc parameter-server processes.

The reference runs dedicated PS processes (BrpcPsServer,
/root/reference/paddle/fluid/distributed/service/brpc_ps_server.cc) that
workers dial for pull_sparse/push_sparse
(brpc_ps_client.cc). Here the same split: :class:`TableServer` hosts
:class:`~paddle1_tpu.distributed.ps.SparseTable` shards behind a TCP
socket; :class:`RemoteTable` is a client with the exact pull/push
interface of a local table, so :class:`EmbeddingService` routes to local
and remote shards identically.

Protocol: length-prefixed pickled (op, payload) tuples over TCP, one
request per round-trip, thread-per-connection on the server. Pickle is
acceptable for the same reason the reference's brpc endpoints are: the
PS protocol runs inside a trusted training cluster, never on a public
interface — bind to cluster-internal addresses only. Defense-in-depth:
set ``PADDLE_PS_SECRET`` (any string, same value on every node) and each
frame carries an HMAC-SHA256 tag that is verified BEFORE the payload is
unpickled, so a stray client that can reach the port but lacks the
secret cannot reach the deserializer.

Fault tolerance (ISSUE 20): the reference's PS survives server death
(``PSERVER`` relaunch + worker reconnect); here the same contract in
three pieces. (1) :class:`TableServer` can checkpoint its own state
(table + aux tables + the push fence) to ``ckpt_dir`` after mutating
requests — tmp+fsync+rename, so a kill leaves the previous checkpoint
intact — and restores from it at construction, which makes it a
restartable :class:`~paddle1_tpu.distributed.supervisor.Supervisor`
worker (``serve_main`` is the subprocess entry; spawn with
``essential=False`` + policy ``restart`` instead of the old
essential=fail-the-job). (2) :class:`RemoteTable` retries with typed
bounded backoff + reconnect (``ft_ps_*`` flags), so a server restart
mid-pull/push is a stall, not a trainer crash; exhaustion raises
:class:`PsUnavailableError`. (3) Mutating requests travel inside a
per-client *push-epoch fence* envelope (monotone sequence + server-side
last-applied map + cached reply, persisted atomically WITH the table
state): a request replayed past a server restart is applied exactly
once — the retry either reaches a server whose checkpoint predates the
request (fresh apply) or one that already applied it (cached reply).

Env contract (reference launch_utils.py PS mode):
``PADDLE_PSERVERS_IP_PORT_LIST`` = comma-separated ``host:port`` of the
table servers; ``TRAINING_ROLE`` = ``PSERVER`` | ``TRAINER``;
``PADDLE_PORT`` = this server's port. ``fleet.init_server/run_server``
consume these (fleet_base.py).
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import os
import pickle
import signal
import socket
import socketserver
import struct
import tempfile
import threading
import time
import uuid
from typing import Optional, Sequence

import numpy as np

from ..core import chaos as _chaos
from ..core.errors import PreconditionNotMetError, UnavailableError
from .ps import SparseTable

__all__ = ["TableServer", "RemoteTable", "remote_service",
           "PsUnavailableError", "serve_main"]

_HDR = struct.Struct("!BI")  # (tag-present flag, payload length)
_MAX_MSG = 1 << 30
_TAG_LEN = hashlib.sha256().digest_size


_SMALL_MSG = 1 << 20

# how long an armed ``ps_hang`` stalls one request: longer than any
# sane client socket timeout, bounded so the daemon handler thread
# eventually unwinds
_HANG_S = 45.0

_CKPT_NAME = "ps-state.pkl"

_log = __import__("logging").getLogger("paddle1_tpu.ps")


class _AuthError(ConnectionError):
    """Frame failed/skipped HMAC authentication (vs. a plain socket
    error): the server logs it and tells the peer why before closing."""


class PsUnavailableError(UnavailableError, ConnectionError):
    """A RemoteTable exhausted its bounded retry/backoff budget against
    an unreachable table server (``ft_ps_max_retries`` reconnect
    attempts). Still a ``ConnectionError`` so pre-retry callers keep
    working; typed so the resilient loop can tell "PS fleet is gone"
    from a transient socket hiccup (which the retries already ate)."""


def _secret() -> Optional[bytes]:
    s = os.environ.get("PADDLE_PS_SECRET")
    return s.encode() if s else None


def _send(sock, obj) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    key = _secret()
    tag = _hmac.new(key, payload, hashlib.sha256).digest() if key else b""
    hdr = _HDR.pack(1 if key else 0, len(payload))
    if len(payload) < _SMALL_MSG:
        # one segment: avoids the Nagle write-write-read stall on the
        # per-step pull/push round-trips (the copy is cheap at this size)
        sock.sendall(hdr + tag + payload)
    else:
        sock.sendall(hdr + tag)
        sock.sendall(payload)  # no second copy of a big body


def _recv(sock):
    hdr = _recv_exact(sock, _HDR.size)
    if hdr is None:
        return None
    tagged, n = _HDR.unpack(hdr)
    if n > _MAX_MSG:
        raise ValueError(f"ps message too large: {n} bytes")
    key = _secret()
    tag = b""
    if tagged:
        tag = _recv_exact(sock, _TAG_LEN)
        if tag is None:
            raise ConnectionError("peer closed mid-message")
    elif key:
        # the flag makes asymmetric configuration a loud error, not a
        # mutual read-hang: without it we would consume payload bytes as
        # a tag and then block waiting for the remainder. Drain the body
        # first so an err reply can be framed on an aligned stream.
        _recv_exact(sock, n)
        raise _AuthError(
            "peer sent an unauthenticated ps frame but this side has "
            "PADDLE_PS_SECRET set — configure the same secret on every "
            "node")
    body = _recv_exact(sock, n)
    if body is None:
        raise ConnectionError("peer closed mid-message")
    if key and not _hmac.compare_digest(
            tag, _hmac.new(key, body, hashlib.sha256).digest()):
        # authenticate BEFORE deserializing: an unauthenticated client
        # never reaches pickle.loads
        raise _AuthError("ps frame failed HMAC authentication "
                         "(PADDLE_PS_SECRET mismatch)")
    return pickle.loads(body)


def _recv_exact(sock, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            if buf:
                raise ConnectionError("peer closed mid-message")
            return None  # clean EOF between messages
        buf += chunk
    return buf


class _Handler(socketserver.BaseRequestHandler):
    def setup(self):
        self.request.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def _dispatch(self, op, payload):
        """One request → one ``("ok", value)`` / ``("err", reason)``
        reply tuple (exceptions propagate to the caller's catch-all)."""
        table: SparseTable = self.server.table  # type: ignore[attr-defined]
        if op == "pull":
            return ("ok", table.pull(payload))
        if op == "push":
            ids, grads = payload
            table.push(ids, grads)
            return ("ok", None)
        if op == "len":
            return ("ok", len(table))
        if op == "state":
            return ("ok", table.state_dict())
        if op == "load":
            table.load_state_dict(payload)
            return ("ok", None)
        if op == "ping":
            return ("ok", "pong")
        if op == "dim":
            return ("ok", table.dim)
        if op in ("call", "tcall"):
            # whitelisted table method, never arbitrary attrs.
            # "call" targets the primary table (GraphTable
            # sampling etc.); "tcall" routes by table NAME
            # (reference: one brpc PS serves many tables by id —
            # a Downpour node pairs its sparse shard with dense
            # blocks on one port).
            if op == "call":
                tname, (method, args, kwargs) = None, payload
            else:
                tname, method, args, kwargs = payload
            aux = self.server.aux_tables  # type: ignore[attr-defined]
            tgt = table if tname is None else aux.get(tname)
            if tgt is None:
                return ("err", f"no table named {tname!r} on this "
                               f"server (have {sorted(aux)})")
            allowed = getattr(tgt, "RPC_METHODS", frozenset())
            if method not in allowed:
                return ("err", f"method {method!r} not in "
                        + ("this table's" if tname is None else
                           f"table {tname!r}'s")
                        + " RPC_METHODS")
            return ("ok", getattr(tgt, method)(*args, **kwargs))
        if op == "tlist":
            return ("ok", sorted(self.server.aux_tables))  # type: ignore[attr-defined]
        return ("err", f"unknown op {op!r}")

    def handle(self):
        owner: "TableServer" = self.server.owner  # type: ignore[attr-defined]
        while True:
            try:
                msg = _recv(self.request)
            except _AuthError as e:
                # surface the misconfiguration on both sides: log here,
                # send the reason to the peer (the reply frame carries a
                # tag the peer simply skips if it has no secret), close
                _log.warning("dropping ps client %s: %s",
                             self.client_address, e)
                try:
                    _send(self.request, ("err", str(e)))
                except OSError:
                    pass
                return
            except (ConnectionError, OSError):
                return
            if msg is None:
                return
            op, payload = msg
            fired = (_chaos.check_ps(owner.rank)
                     if _chaos.enabled() else None)
            if fired == _chaos.PS_HANG:
                # a wedged PS: stall past the client's socket timeout —
                # the retry/reconnect path must turn this into a stall,
                # not a trainer crash (a late reply hits a closed
                # socket and is swallowed below)
                time.sleep(_HANG_S)
            try:
                if op == "x":
                    # push-epoch fence envelope: (client, seq, inner).
                    # seq <= last-applied returns the CACHED reply —
                    # the retry-past-restart replay is applied exactly
                    # once whether or not the dead server got to it.
                    client, seq, inner_op, inner_payload = payload
                    with owner._mut_lock:
                        last, cached = owner._fence.get(
                            client, (0, ("ok", None)))
                        if seq <= last:
                            reply = cached
                        else:
                            reply = self._dispatch(inner_op,
                                                   inner_payload)
                            owner._fence[client] = (seq, reply)
                            owner._note_mutation_locked()
                elif op == "shutdown":
                    if fired == _chaos.PS_KILL:
                        os.kill(os.getpid(), signal.SIGKILL)
                    _send(self.request, ("ok", None))

                    def _stop(server=self.server):
                        server.shutdown()
                        server.server_close()  # release the listening fd
                    threading.Thread(target=_stop, daemon=True).start()
                    return
                else:
                    reply = self._dispatch(op, payload)
                    if op in ("push", "load"):
                        # legacy unfenced mutations still ride the
                        # checkpoint cadence
                        with owner._mut_lock:
                            owner._note_mutation_locked()
                if fired == _chaos.PS_KILL:
                    # die AFTER applying + checkpointing, BEFORE the
                    # ack: the client must replay and the fence must
                    # keep the replay idempotent
                    os.kill(os.getpid(), signal.SIGKILL)
                _send(self.request, reply)
            except Exception as e:  # keep serving other workers
                try:
                    _send(self.request, ("err", f"{type(e).__name__}: {e}"))
                except OSError:
                    return


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class TableServer:
    """Serve ONE SparseTable shard over TCP (the reference's one
    brpc_ps_server process per PS node). ``serve_forever`` blocks (use
    from ``fleet.run_server``); ``start`` runs in a background thread
    (tests, notebooks).

    With ``ckpt_dir`` set the server is *restartable*: it checkpoints
    its table, aux tables and push fence after every ``save_every``-th
    mutating request (tmp+fsync+rename — a SIGKILL mid-write leaves the
    previous checkpoint intact) and restores from the newest checkpoint
    at construction. Together with the client-side fence envelope this
    gives exactly-once pushes across a kill/restart."""

    def __init__(self, table: SparseTable, host: str = "127.0.0.1",
                 port: int = 0, aux_tables: Optional[dict] = None,
                 ckpt_dir: Optional[str] = None, save_every: int = 1,
                 rank: int = 0):
        self.table = table
        self.aux_tables = dict(aux_tables or {})
        self.ckpt_dir = str(ckpt_dir) if ckpt_dir else None
        self.save_every = max(1, int(save_every))
        self.rank = int(rank)
        # fence: client-id -> (last applied seq, cached reply); guarded
        # by _mut_lock together with checkpoint writes so a checkpoint
        # can never observe an apply without its fence advance
        self._fence: dict = {}
        self._mut_lock = threading.Lock()
        self._mutations = 0
        if self.ckpt_dir:
            os.makedirs(self.ckpt_dir, exist_ok=True)
            self.restore_checkpoint()
        self._srv = _TCPServer((host, port), _Handler)
        self._srv.table = table  # type: ignore[attr-defined]
        # named side tables on the same port (dense blocks beside the
        # sparse shard — the reference's multi-table PS node)
        self._srv.aux_tables = self.aux_tables  # type: ignore[attr-defined]
        self._srv.owner = self  # type: ignore[attr-defined]
        self.host, self.port = self._srv.server_address[:2]
        self._thread: Optional[threading.Thread] = None
        self._stopped = False

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    # -- restartable-worker state ------------------------------------------

    def _ckpt_path(self) -> str:
        return os.path.join(self.ckpt_dir, _CKPT_NAME)

    def save_checkpoint(self) -> Optional[str]:
        """Atomically persist table + aux tables + fence (no-op without
        ``ckpt_dir``). Returns the checkpoint path."""
        if not self.ckpt_dir:
            return None
        state = {
            "table": self.table.state_dict(),
            "aux": {name: t.state_dict()
                    for name, t in self.aux_tables.items()
                    if hasattr(t, "state_dict")},
            "fence": dict(self._fence),
        }
        blob = pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL)
        fd, tmp = tempfile.mkstemp(dir=self.ckpt_dir, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._ckpt_path())
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return self._ckpt_path()

    def restore_checkpoint(self) -> bool:
        """Load the newest checkpoint from ``ckpt_dir`` (False when
        there is none — a first launch)."""
        if not self.ckpt_dir:
            return False
        path = self._ckpt_path()
        try:
            with open(path, "rb") as f:
                state = pickle.load(f)
        except FileNotFoundError:
            return False
        self.table.load_state_dict(state["table"])
        for name, s in state.get("aux", {}).items():
            t = self.aux_tables.get(name)
            if t is not None and hasattr(t, "load_state_dict"):
                t.load_state_dict(s)
        self._fence = dict(state.get("fence", {}))
        return True

    def _note_mutation_locked(self) -> None:
        """Called by the handler (holding ``_mut_lock``) after a
        mutating request; checkpoints every ``save_every``-th one."""
        self._mutations += 1
        if self.ckpt_dir and self._mutations % self.save_every == 0:
            self.save_checkpoint()

    # -- lifecycle ---------------------------------------------------------

    def serve_forever(self):
        self._srv.serve_forever()

    def start(self) -> "TableServer":
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        """Idempotent: safe to call twice, and after a remote
        ``shutdown`` op already closed the listener."""
        if self._stopped:
            return
        self._stopped = True
        try:
            self._srv.shutdown()
            self._srv.server_close()
        except OSError:
            pass  # remote shutdown op already released the fd
        if self._thread is not None:
            self._thread.join(timeout=5)


def _ps_flag(name: str, default):
    try:
        from ..core import flags as core_flags
        v = core_flags.flag(name)
    except Exception:
        return default
    return default if v is None else v


class RemoteTable:
    """Client-side twin of SparseTable: same pull/push/state interface,
    rows live in the server process (brpc_ps_client.cc pull_sparse/
    push_sparse). One persistent connection, lock-serialized (matching
    the per-table lock of the local shard).

    Transient transport failures (server restarting, wedged request,
    refused connect) are retried with bounded exponential backoff and a
    fresh connection per attempt (``ft_ps_max_retries`` /
    ``ft_ps_backoff_base_s`` / ``ft_ps_backoff_max_s``); exhaustion
    raises :class:`PsUnavailableError`. Mutating ops (push, load,
    call/tcall) ride the fence envelope, so a retry that replays a
    request the dead server already applied gets the cached reply
    instead of a double-applied gradient."""

    def __init__(self, endpoint: str, timeout: float = 30.0,
                 max_retries: Optional[int] = None,
                 backoff_base_s: Optional[float] = None,
                 backoff_max_s: Optional[float] = None):
        host, port = endpoint.rsplit(":", 1)
        self.endpoint = endpoint
        self._addr = (host, int(port))
        self._timeout = float(timeout)
        self._retries = int(_ps_flag("ft_ps_max_retries", 5)
                            if max_retries is None else max_retries)
        self._backoff_base = float(_ps_flag("ft_ps_backoff_base_s", 0.05)
                                   if backoff_base_s is None
                                   else backoff_base_s)
        self._backoff_max = float(_ps_flag("ft_ps_backoff_max_s", 2.0)
                                  if backoff_max_s is None
                                  else backoff_max_s)
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        # push-epoch fence identity: client id + monotone sequence
        # (allocated under _lock, so the server sees seqs in order)
        self._client_id = uuid.uuid4().hex
        self._seq = 0
        self.dim = self._call("dim")  # also validates the connection

    def _connect(self) -> None:
        self._sock = socket.create_connection(self._addr,
                                              timeout=self._timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def _close_sock(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _call(self, op, payload=None, fenced: bool = False):
        from ..obs.registry import process_registry as _reg
        with self._lock:
            if fenced:
                self._seq += 1
                op, payload = "x", (self._client_id, self._seq, op,
                                    payload)
            attempts = 0
            while True:
                try:
                    if self._sock is None:
                        self._connect()
                        if attempts:
                            _reg().counter(
                                "ft_ps_reconnects_total").inc()
                    _send(self._sock, (op, payload))
                    reply = _recv(self._sock)
                    if reply is None:
                        raise ConnectionError(
                            f"table server {self.endpoint} closed the "
                            f"connection")
                    break
                except _AuthError:
                    # deterministic misconfiguration: retrying cannot
                    # help and would just hammer the server
                    self._close_sock()
                    raise
                except (ConnectionError, OSError) as e:
                    self._close_sock()
                    attempts += 1
                    if attempts > self._retries:
                        _reg().counter("ft_ps_unavailable_total").inc()
                        raise PsUnavailableError(
                            f"table server {self.endpoint} unreachable "
                            f"after {self._retries} retries "
                            f"(last error: {type(e).__name__}: {e}) — "
                            f"is the PS worker running / being "
                            f"restarted by its Supervisor?") from e
                    _reg().counter("ft_ps_retries_total").inc()
                    # backoff must hold the op lock: ops on this client
                    # share one socket and strictly ordered fence seqs,
                    # so letting another thread jump the queue here
                    # would reorder fenced mutations on the wire
                    time.sleep(min(  # noqa: lock-blocking — see above
                        self._backoff_base * (2 ** (attempts - 1)),
                        self._backoff_max))
        status, out = reply
        if status != "ok":
            raise PreconditionNotMetError(f"table server {self.endpoint}: "
                                          f"{out}")
        return out

    def pull(self, ids: Sequence[int]) -> np.ndarray:
        return self._call("pull", np.asarray(ids, np.int64))

    def push(self, ids: Sequence[int], grads) -> None:
        self._call("push", (np.asarray(ids, np.int64),
                            np.asarray(grads, np.float32)), fenced=True)

    # tier-bridge surface: rows + optimizer slots move across the wire
    # (SparseTable whitelists both in RPC_METHODS), so the remote
    # cluster tier composes with the HBM/host demote-promote machinery
    # exactly like a local shard

    def has(self, ids: Sequence[int]) -> np.ndarray:
        return self.call("has", np.asarray(ids, np.int64))

    def evict(self, ids: Sequence[int], create: bool = False) -> dict:
        return self.call("evict", np.asarray(ids, np.int64),
                         create=create)

    def admit(self, ids: Sequence[int], rows, slots=None,
              steps=None) -> None:
        self.call("admit", np.asarray(ids, np.int64),
                  np.asarray(rows, np.float32), slots, steps)

    def __len__(self) -> int:
        return self._call("len")

    def state_dict(self) -> dict:
        return self._call("state")

    def load_state_dict(self, state: dict) -> None:
        self._call("load", state, fenced=True)

    def ping(self) -> bool:
        return self._call("ping") == "pong"

    def call(self, method: str, *args, **kwargs):
        """Invoke a whitelisted table method remotely (GraphTable's
        sampling surface and other non-embedding tables). Fenced: a
        mutating method (evict/admit) replayed past a server restart is
        applied exactly once."""
        return self._call("call", (method, args, kwargs), fenced=True)

    def table_call(self, table_name: Optional[str], method: str, *args,
                   **kwargs):
        """Invoke a whitelisted method on a NAMED table of this server
        (dense blocks served beside the sparse shard); ``None`` targets
        the primary table."""
        return self._call("tcall", (table_name, method, args, kwargs),
                          fenced=True)

    def list_tables(self):
        return self._call("tlist")

    def shutdown_server(self) -> None:
        self._call("shutdown")

    def close(self):
        with self._lock:
            self._close_sock()


def remote_service(dim: int, endpoints: Sequence[str]):
    """EmbeddingService whose shards are RemoteTables — one per server
    endpoint, routed by ``id % num_shards`` exactly like local shards
    (the reference's shard_num partition over PS nodes)."""
    from .ps import EmbeddingService
    return EmbeddingService(dim, shards=[RemoteTable(ep)
                                         for ep in endpoints])


def serve_main(argv=None) -> None:
    """Subprocess entry for a *supervised* table server::

        python -m paddle1_tpu.distributed.ps_server \\
            --dim 16 --port 7100 --ckpt-dir /ckpts/ps0 --rank 0

    Registered with the Supervisor as ``essential=False`` + policy
    ``restart``: a death is a restart-from-own-checkpoint (state +
    fence), not a failed job. Heartbeats ride ``core.health.beat`` so
    the hang detector covers a wedged server; chaos points are armed
    from ``FLAGS_ft_chaos`` only in incarnation 0, so the restarted
    life replays clean (the fire-once contract every chaos point
    keeps)."""
    import argparse
    ap = argparse.ArgumentParser(
        description="paddle1_tpu table server (supervised PS worker)")
    ap.add_argument("--dim", type=int, required=True)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--optimizer", default="sgd")
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--save-every", type=int, default=1)
    ap.add_argument("--rank", type=int, default=0)
    ap.add_argument("--init", choices=("default", "zeros"),
                    default="default",
                    help="row initializer; 'zeros' keeps fresh rows "
                         "deterministic across restarts (the chaos "
                         "parity soak's setting)")
    args = ap.parse_args(argv)
    from ..core import health
    incarnation = int(os.environ.get(health.INCARNATION_ENV, "0") or 0)
    if incarnation == 0:
        _chaos.configure_from_flags()
    init = ((lambda rng, dim: np.zeros(dim, np.float32))
            if args.init == "zeros" else None)
    table = SparseTable(args.dim, initializer=init,
                        optimizer=args.optimizer, lr=args.lr)
    srv = TableServer(table, host=args.host, port=args.port,
                      ckpt_dir=args.ckpt_dir,
                      save_every=args.save_every, rank=args.rank)

    def _beat_loop():
        while True:
            health.beat()
            time.sleep(0.5)

    threading.Thread(target=_beat_loop, daemon=True).start()
    restored = bool(args.ckpt_dir) and os.path.exists(
        os.path.join(args.ckpt_dir, _CKPT_NAME))
    print(f"ps-server rank {args.rank} listening on {srv.endpoint} "
          f"(incarnation {incarnation}, restored={restored})",
          flush=True)
    srv.serve_forever()


if __name__ == "__main__":  # pragma: no cover - subprocess entry
    serve_main()
