"""Network transport for the embedding-table service — the scoped analog
of the reference's brpc parameter-server processes.

The reference runs dedicated PS processes (BrpcPsServer,
/root/reference/paddle/fluid/distributed/service/brpc_ps_server.cc) that
workers dial for pull_sparse/push_sparse
(brpc_ps_client.cc). Here the same split: :class:`TableServer` hosts
:class:`~paddle1_tpu.distributed.ps.SparseTable` shards behind a TCP
socket; :class:`RemoteTable` is a client with the exact pull/push
interface of a local table, so :class:`EmbeddingService` routes to local
and remote shards identically.

Protocol: length-prefixed pickled (op, payload) tuples over TCP, one
request per round-trip, thread-per-connection on the server. Pickle is
acceptable for the same reason the reference's brpc endpoints are: the
PS protocol runs inside a trusted training cluster, never on a public
interface — bind to cluster-internal addresses only.

Env contract (reference launch_utils.py PS mode):
``PADDLE_PSERVERS_IP_PORT_LIST`` = comma-separated ``host:port`` of the
table servers; ``TRAINING_ROLE`` = ``PSERVER`` | ``TRAINER``;
``PADDLE_PORT`` = this server's port. ``fleet.init_server/run_server``
consume these (fleet_base.py).
"""

from __future__ import annotations

import pickle
import socket
import socketserver
import struct
import threading
from typing import Optional, Sequence

import numpy as np

from ..core.errors import PreconditionNotMetError
from .ps import SparseTable

__all__ = ["TableServer", "RemoteTable", "remote_service"]

_HDR = struct.Struct("!I")
_MAX_MSG = 1 << 30


_SMALL_MSG = 1 << 20


def _send(sock, obj) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    if len(payload) < _SMALL_MSG:
        # one segment: avoids the Nagle write-write-read stall on the
        # per-step pull/push round-trips (the copy is cheap at this size)
        sock.sendall(_HDR.pack(len(payload)) + payload)
    else:
        sock.sendall(_HDR.pack(len(payload)))
        sock.sendall(payload)  # no second copy of a big body


def _recv(sock):
    hdr = _recv_exact(sock, _HDR.size)
    if hdr is None:
        return None
    (n,) = _HDR.unpack(hdr)
    if n > _MAX_MSG:
        raise ValueError(f"ps message too large: {n} bytes")
    body = _recv_exact(sock, n)
    if body is None:
        raise ConnectionError("peer closed mid-message")
    return pickle.loads(body)


def _recv_exact(sock, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            if buf:
                raise ConnectionError("peer closed mid-message")
            return None  # clean EOF between messages
        buf += chunk
    return buf


class _Handler(socketserver.BaseRequestHandler):
    def setup(self):
        self.request.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def handle(self):
        table: SparseTable = self.server.table  # type: ignore[attr-defined]
        while True:
            try:
                msg = _recv(self.request)
            except (ConnectionError, OSError):
                return
            if msg is None:
                return
            op, payload = msg
            try:
                if op == "pull":
                    _send(self.request, ("ok", table.pull(payload)))
                elif op == "push":
                    ids, grads = payload
                    table.push(ids, grads)
                    _send(self.request, ("ok", None))
                elif op == "len":
                    _send(self.request, ("ok", len(table)))
                elif op == "state":
                    _send(self.request, ("ok", table.state_dict()))
                elif op == "load":
                    table.load_state_dict(payload)
                    _send(self.request, ("ok", None))
                elif op == "ping":
                    _send(self.request, ("ok", "pong"))
                elif op == "dim":
                    _send(self.request, ("ok", table.dim))
                elif op == "call":
                    # generic table method — whitelisted per table class
                    # (GraphTable sampling ops etc.); never arbitrary attrs
                    method, args, kwargs = payload
                    allowed = getattr(table, "RPC_METHODS", frozenset())
                    if method not in allowed:
                        _send(self.request,
                              ("err", f"method {method!r} not in this "
                                      f"table's RPC_METHODS"))
                    else:
                        _send(self.request,
                              ("ok", getattr(table, method)(*args,
                                                            **kwargs)))
                elif op == "shutdown":
                    _send(self.request, ("ok", None))

                    def _stop(server=self.server):
                        server.shutdown()
                        server.server_close()  # release the listening fd
                    threading.Thread(target=_stop, daemon=True).start()
                    return
                else:
                    _send(self.request, ("err", f"unknown op {op!r}"))
            except Exception as e:  # keep serving other workers
                try:
                    _send(self.request, ("err", f"{type(e).__name__}: {e}"))
                except OSError:
                    return


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class TableServer:
    """Serve ONE SparseTable shard over TCP (the reference's one
    brpc_ps_server process per PS node). ``serve_forever`` blocks (use
    from ``fleet.run_server``); ``start`` runs in a background thread
    (tests, notebooks)."""

    def __init__(self, table: SparseTable, host: str = "127.0.0.1",
                 port: int = 0):
        self._srv = _TCPServer((host, port), _Handler)
        self._srv.table = table  # type: ignore[attr-defined]
        self.table = table
        self.host, self.port = self._srv.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    def serve_forever(self):
        self._srv.serve_forever()

    def start(self) -> "TableServer":
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)


class RemoteTable:
    """Client-side twin of SparseTable: same pull/push/state interface,
    rows live in the server process (brpc_ps_client.cc pull_sparse/
    push_sparse). One persistent connection, lock-serialized (matching
    the per-table lock of the local shard)."""

    def __init__(self, endpoint: str, timeout: float = 30.0):
        host, port = endpoint.rsplit(":", 1)
        self.endpoint = endpoint
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()
        self.dim = self._call("dim")  # also validates the connection

    def _call(self, op, payload=None):
        with self._lock:
            _send(self._sock, (op, payload))
            reply = _recv(self._sock)
        if reply is None:
            raise ConnectionError(
                f"table server {self.endpoint} closed the connection")
        status, out = reply
        if status != "ok":
            raise PreconditionNotMetError(f"table server {self.endpoint}: "
                                          f"{out}")
        return out

    def pull(self, ids: Sequence[int]) -> np.ndarray:
        return self._call("pull", np.asarray(ids, np.int64))

    def push(self, ids: Sequence[int], grads) -> None:
        self._call("push", (np.asarray(ids, np.int64),
                            np.asarray(grads, np.float32)))

    def __len__(self) -> int:
        return self._call("len")

    def state_dict(self) -> dict:
        return self._call("state")

    def load_state_dict(self, state: dict) -> None:
        self._call("load", state)

    def ping(self) -> bool:
        return self._call("ping") == "pong"

    def call(self, method: str, *args, **kwargs):
        """Invoke a whitelisted table method remotely (GraphTable's
        sampling surface and other non-embedding tables)."""
        return self._call("call", (method, args, kwargs))

    def shutdown_server(self) -> None:
        self._call("shutdown")

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


def remote_service(dim: int, endpoints: Sequence[str]):
    """EmbeddingService whose shards are RemoteTables — one per server
    endpoint, routed by ``id % num_shards`` exactly like local shards
    (the reference's shard_num partition over PS nodes)."""
    from .ps import EmbeddingService
    return EmbeddingService(dim, shards=[RemoteTable(ep)
                                         for ep in endpoints])
