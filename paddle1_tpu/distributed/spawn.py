"""paddle.distributed.spawn analog (reference
python/paddle/distributed/spawn.py:321): multiprocessing alternative to the
launcher for single-host multi-process runs. On TPU, multi-process per host
is only meaningful for CPU-simulated rank testing — real chips are driven by
one process — so spawn runs the function in subprocesses with the launcher's
env protocol.
"""

from __future__ import annotations

import multiprocessing as mp
import os
from typing import Optional, Tuple

__all__ = ["spawn"]


def _worker(fn, rank: int, nprocs: int, args):
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    os.environ["RANK"] = str(rank)
    os.environ["WORLD_SIZE"] = str(nprocs)
    fn(*args)


def spawn(func, args: Tuple = (), nprocs: int = 1, join: bool = True,
          daemon: bool = False, **options):
    if nprocs <= 1:
        _worker(func, 0, 1, args)
        return None
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        p = ctx.Process(target=_worker, args=(func, rank, nprocs, args),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
        for p in procs:
            if p.exitcode != 0:
                raise RuntimeError(
                    f"spawned rank exited with code {p.exitcode}")
    return procs
