"""Elastic supervision for the multi-process launcher.

The reference watch loop (``launch_utils.py:559 watch_local_trainers``,
kept here as the unsupervised default) is pure fail-fast: any worker
death kills the pod, and a worker that *hangs* — deadlocked queue,
stuck collective, wedged host callback — is never detected at all. The
:class:`Supervisor` is the layer the ROADMAP's production north-star
needs above PR 2's single-process ``ResilientTrainer``: it owns the
worker subprocesses, gives each a heartbeat channel (the worker half
lives in :mod:`paddle1_tpu.core.health`; workers call ``health.beat()``
every step), and detects three failure classes:

* **exit** — ``poll()`` returned nonzero (or an *essential* worker,
  e.g. a parameter server, exited at all while the job still runs);
* **hang** — the per-rank heartbeat file is older than
  ``ft_hang_timeout`` (before killing, the supervisor sends ``SIGABRT``
  so the worker's registered ``faulthandler`` writes an all-threads
  stack dump to the log dir — wedged collectives become diagnosable);
* **unhealthy** — the worker explicitly reported itself broken via
  ``health.report_unhealthy`` (marker file beside the heartbeat).

Response is per policy (flag ``ft_supervise``):

``fail_fast``
    Today's semantics plus hang *detection*: first failure kills the
    pod; the failure's exit code (or 1) is the return code.
``restart``
    SIGKILL the failed/hung rank and relaunch it with the same command
    and env (incarnation counter bumped) up to
    ``ft_max_worker_restarts`` times per rank; the other ranks keep
    running. The relaunched worker resumes from the last committed
    checkpoint (PR 2 ``ResilientTrainer.restore_latest``), so a
    killed-and-restarted run must match the uninterrupted run to 1e-6 —
    the elastic parity gate (``bench.py --elastic``,
    tests/test_launch.py). In a **multi-worker world** a dead rank
    cannot rejoin live collectives, so ``restart`` routes the failure
    into the *resize* path below (shrink-and-continue) instead of
    relaunching the lone rank into a job that can no longer hear it.
``drain``
    Request graceful preemption from every worker (SIGTERM → the
    ``health`` SIGTERM handler calls ``chaos.request_preemption()`` and
    marks a drain, so ``ResilientTrainer.fit`` checkpoints its current
    good state and stops), wait out a grace window, then stop the pod.
``resize``
    Membership change is a *recoverable event*, not a fatal one. On
    worker loss (or an explicit :meth:`Supervisor.request_resize`) the
    surviving ranks are drained (SIGTERM → each ``ResilientTrainer``
    commits a final atomic checkpoint, whose manifest carries the
    mesh/topology descriptor), the world size is recomputed, and the
    fleet relaunches at the new size with resume-from-latest: each new
    worker rebuilds its mesh via ``topology.plan_resize`` and the
    restore reshards param/optimizer state through the manifest-driven
    old-shard → new-shard remap (``checkpoint.load_sharded``). Budgets:
    ``ft_elastic_min_world`` is the shrink floor, ``ft_max_resizes``
    bounds total membership churn. ``bench.py --elastic-resize`` is the
    8→6→8 parity gate.

Policy × failure matrix (adopted = ``attach``'d, no respawn spec)::

    policy     exit/hang/unhealthy rank      essential worker   adopted
    fail_fast  kill pod                      kill pod           kill pod
    restart    world=1: relaunch rank        kill pod           kill pod
               world>1: resize (shrink)
    drain      checkpoint all, stop pod      kill pod           drain
    resize     shrink-and-continue           kill pod           kill pod

The supervisor also *adopts* pre-spawned processes (``attach``) so the
legacy ``watch_local_trainers`` / ``watch_ps_procs`` surfaces — and
``fleet.ProcessMultiTrainer``'s ``multiprocessing`` workers, via
:class:`MpProcessHandle` — run on the same loop; adopted workers have
no respawn spec, so ``restart``/``resize`` fall back to ``fail_fast``
for them.

Non-trainer adoption (the serving fleet): :meth:`run`'s loop is shaped
around a *job that finishes* — every trainer exits 0 and the pod is
done. Long-lived worker pools (serving replicas) instead EMBED the
supervisor: register respawnable workers, then call
:meth:`supervise_once` from their own loop — one detection sweep that
applies the per-rank ``restart`` policy (heartbeat hang detection,
stack dumps, restart budgets, all identical to the trainer path) but
never decides the pod is finished or failed; it returns
:class:`SupervisionEvent` records and the embedding owner
(``serving.fleet.ServingFleet``) decides what a permanent failure
means. :meth:`spawn_worker` / :meth:`restart_rank` / :meth:`retire`
give that owner explicit lifecycle control (a model hot-swap retires
old replicas and spawns new ones mid-flight), and per-worker
``max_restarts`` overrides let a deploy canary run with a zero budget
while the standing fleet keeps the full one.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..core import flags as core_flags
from ..core import locks as core_locks
from ..core.errors import InvalidArgumentError
from ..core.health import (HEARTBEAT_ENV, INCARNATION_ENV, STACKDUMP_ENV,
                           UNHEALTHY_SUFFIX)

__all__ = ["Supervisor", "SupervisorReport", "WorkerFailure",
           "SupervisionEvent", "MpProcessHandle", "POLICIES"]

POLICIES = ("fail_fast", "restart", "drain", "resize")

# failure kinds
EXIT = "exit"
HANG = "hang"
UNHEALTHY = "unhealthy"


@dataclass
class WorkerFailure:
    """One detected failure (what check_failed()/the policy loop see)."""
    rank: int
    kind: str                      # exit | hang | unhealthy
    exit_code: Optional[int] = None
    reason: str = ""
    stack_dump: Optional[str] = None
    # the uncoerced returncode (an essential worker's CLEAN exit is
    # reported with exit_code 1 but raw_exit 0 — the run loop forgives
    # it when the trainers finished in the same sweep)
    raw_exit: Optional[int] = None


@dataclass
class SupervisionEvent:
    """One :meth:`Supervisor.supervise_once` outcome: what was detected
    and what the sweep did about it."""
    failure: WorkerFailure
    # "restarted"         — restart policy relaunched the rank in place
    # "restart_exhausted" — out of budget; the rank stays down (and a
    #                       wedged-but-alive process was SIGKILLed) —
    #                       the embedding owner decides what that means
    # "detected"          — no automatic response applies (policy is not
    #                       restart, or the worker has no respawn spec)
    action: str = "detected"

    @property
    def rank(self) -> int:
        return self.failure.rank


# ResizeRefused reasons: the two limits elasticity can hit. An
# autoscaler backs off differently per reason — below_floor means the
# request itself was out of policy (clamp and move on), budget
# exhausted means the WORLD is out of membership churn (stop asking).
RESIZE_BELOW_FLOOR = "below_floor"
RESIZE_BUDGET_EXHAUSTED = "budget_exhausted"


@dataclass(frozen=True)
class ResizeRefused:
    """Typed refusal from :meth:`Supervisor.request_resize` (and the
    non-strict ``_resize`` path): which limit was hit, what was asked,
    and where the limit sits — enough for a caller to back off
    correctly instead of re-parsing stderr."""
    reason: str                    # RESIZE_BELOW_FLOOR | RESIZE_BUDGET_EXHAUSTED
    requested: int                 # the world size that was refused
    limit: int                     # the floor / budget that refused it
    detail: str = ""

    def __str__(self) -> str:
        return (f"resize to {self.requested} refused "
                f"({self.reason}, limit {self.limit})"
                + (f": {self.detail}" if self.detail else ""))


@dataclass
class SupervisorReport:
    """What the supervision loop actually did — the counters the elastic
    acceptance matrix checks."""
    policy: str = "fail_fast"
    restarts: Dict[int, int] = field(default_factory=dict)  # rank -> n
    failures: List[WorkerFailure] = field(default_factory=list)
    hangs_detected: int = 0
    unhealthy_reports: int = 0
    stack_dumps: List[str] = field(default_factory=list)
    drained: bool = False
    exit_code: Optional[int] = None
    # elastic membership changes: [{"from", "to", "reason"}] in order
    resizes: List[Dict[str, Any]] = field(default_factory=list)
    # refused membership changes, same order discipline:
    # [{"requested", "reason", "limit"}]
    resize_refusals: List[Dict[str, Any]] = field(default_factory=list)
    world_size: Optional[int] = None  # current logical world
    # the CollectiveDivergenceError message when the sweep-time
    # cross-rank verifier caught a diverging schedule (ISSUE 14)
    collective_divergence: Optional[str] = None

    @property
    def total_restarts(self) -> int:
        return sum(self.restarts.values())

    def as_dict(self) -> Dict[str, Any]:
        return {"policy": self.policy,
                "restarts": dict(self.restarts),
                "total_restarts": self.total_restarts,
                "failures": [(f.rank, f.kind, f.exit_code)
                             for f in self.failures],
                "hangs_detected": self.hangs_detected,
                "unhealthy_reports": self.unhealthy_reports,
                "stack_dumps": list(self.stack_dumps),
                "drained": self.drained,
                "resizes": [dict(r) for r in self.resizes],
                "resize_refusals": [dict(r)
                                    for r in self.resize_refusals],
                "world_size": self.world_size,
                "collective_divergence": self.collective_divergence,
                "exit_code": self.exit_code}


class MpProcessHandle:
    """Popen-shaped adapter over a ``multiprocessing.Process`` so the
    Supervisor can watch fleet worker processes with the same loop."""

    def __init__(self, proc):
        self._p = proc

    @property
    def pid(self):
        return self._p.pid

    def poll(self) -> Optional[int]:
        return None if self._p.is_alive() else self._p.exitcode

    def send_signal(self, sig) -> None:
        if self._p.pid is not None and self._p.is_alive():
            os.kill(self._p.pid, sig)

    def terminate(self) -> None:
        self._p.terminate()

    def kill(self) -> None:
        self._p.kill()

    def wait(self, timeout=None) -> Optional[int]:
        self._p.join(timeout)
        return self._p.exitcode


class _Worker:
    """One supervised rank: the (re)spawn spec plus runtime state."""

    def __init__(self, rank: int, cmd: Optional[List[str]] = None,
                 env: Optional[dict] = None,
                 log_path: Optional[str] = None, role: str = "trainer",
                 essential: bool = False, proc=None,
                 max_restarts: Optional[int] = None):
        self.rank = rank
        self.cmd = list(cmd) if cmd is not None else None
        # base_env is the REGISTERED env; env is what the next spawn
        # uses (resize overlays world coordinates onto a fresh copy of
        # base_env each time, so overlays never stack)
        self.base_env = dict(env) if env is not None else None
        self.env = dict(env) if env is not None else None
        self.log_path = log_path
        self.role = role
        self.essential = essential
        self.proc = proc
        self.incarnation = 0
        self.hb_file: Optional[str] = None
        self.hb_spawn_mtime: Optional[float] = None
        self.dump_path: Optional[str] = None
        self.done = False            # exited 0 (role-complete)
        # a permanent failure supervise_once already reported: the
        # corpse must not re-classify (and re-report) every sweep
        self.abandoned = False
        # per-worker restart-budget override (None -> the supervisor's
        # max_restarts); a deploy canary runs with 0 while the standing
        # fleet keeps the full budget
        self.max_restarts = max_restarts
        self.log_fh = None

    @property
    def respawnable(self) -> bool:
        return self.cmd is not None


class Supervisor:
    """Heartbeat-supervised pod of worker processes (module docstring).

    Parameters default from the ``ft_*`` flag registry:
    ``policy`` <- ``ft_supervise`` (empty flag -> ``fail_fast``;
    enabling supervision at all is the *caller's* choice — see
    ``launch.py --ft_supervise``), ``hang_timeout`` <-
    ``ft_hang_timeout``, ``max_restarts`` <- ``ft_max_worker_restarts``.

    ``heartbeat_dir`` holds the per-rank heartbeat + stack-dump files
    (defaults to ``log_dir`` when given, else a mkdtemp).
    ``startup_grace_s`` widens the hang window until a worker's FIRST
    beat (import + XLA compile of a big model can dwarf the steady-state
    step time; default ``5 * hang_timeout``). ``hang_timeout=None`` plus
    no heartbeat dir (pure ``attach`` use) degrades to exit-only
    watching — exactly the legacy semantics.

    Elastic (policy ``resize``, and multi-worker ``restart``) knobs:

    ``world_size``
        The job's *logical* world. Defaults to the number of
        respawnable trainers, the one-process-per-rank fleet; a
        single-controller fleet (one host process driving a W-device
        mesh) registers one worker and passes ``world_size=W`` — a
        resize then relaunches the same process count with new world
        coordinates instead of changing it.
    ``min_world`` / ``max_resizes``
        Shrink floor (flag ``ft_elastic_min_world``) and total
        membership-churn budget (flag ``ft_max_resizes``).
    ``resize_env_hook``
        ``fn(rank, new_world) -> {env}`` merged over the worker's
        registered env at every (re)spawn after a resize — the caller's
        chance to recompute endpoints / device topology (e.g. the CPU
        sim's ``XLA_FLAGS`` device count). The supervisor itself always
        sets ``PADDLE_ELASTIC_WORLD`` and, for per-rank fleets,
        ``PADDLE_TRAINER_ID`` / ``PADDLE_TRAINERS_NUM``.
    ``shrink_target``
        ``fn(current_world, failures) -> new_world`` policy for how far
        a failure shrinks the world (default: one per failed rank).
    ``resize_grace_s``
        Drain window for survivors to commit their final checkpoint
        before relaunch (defaults to ``grace_s``); stragglers are
        SIGKILLed — their last *periodic* commit is then the resume
        point, which the atomic-manifest protocol makes safe.
    ``elastic``
        Override for the failure→resize routing. ``None`` (default)
        = auto: policy ``resize``, or ``restart`` in a multi-worker
        world. ``False`` forces per-rank semantics — what a MULTI-NODE
        launcher must pass, because a per-node supervisor owns only its
        own pod's (global) ranks and must not rebuild a world it
        cannot see (launch.py does this; elastic resize assumes ONE
        supervisor owning every rank, numbered 0..world-1).
    """

    def __init__(self, policy: Optional[str] = None,
                 hang_timeout: Optional[float] = None,
                 max_restarts: Optional[int] = None,
                 heartbeat_dir: Optional[str] = None,
                 log_dir: Optional[str] = None,
                 poll_s: float = 0.5, grace_s: float = 10.0,
                 dump_wait_s: float = 5.0,
                 startup_grace_s: Optional[float] = None,
                 world_size: Optional[int] = None,
                 min_world: Optional[int] = None,
                 max_resizes: Optional[int] = None,
                 resize_env_hook=None, shrink_target=None,
                 resize_grace_s: Optional[float] = None,
                 elastic: Optional[bool] = None):
        if policy is None:
            policy = core_flags.flag("ft_supervise")
        if policy in ("", "off"):
            policy = "fail_fast"
        if policy not in POLICIES:
            raise InvalidArgumentError(
                f"supervision policy must be one of {POLICIES}, "
                f"got {policy!r}")
        self.policy = policy
        self.hang_timeout = float(
            core_flags.flag("ft_hang_timeout") if hang_timeout is None
            else hang_timeout)
        self.max_restarts = int(
            core_flags.flag("ft_max_worker_restarts") if max_restarts is None
            else max_restarts)
        self.log_dir = log_dir
        self._hb_dir = heartbeat_dir
        self.poll_s = float(poll_s)
        self.grace_s = float(grace_s)
        self.dump_wait_s = float(dump_wait_s)
        self.startup_grace_s = (5.0 * self.hang_timeout
                                if startup_grace_s is None
                                else float(startup_grace_s))
        self.world_size = None if world_size is None else int(world_size)
        self.min_world = int(
            core_flags.flag("ft_elastic_min_world") if min_world is None
            else min_world)
        self.max_resizes = int(
            core_flags.flag("ft_max_resizes") if max_resizes is None
            else max_resizes)
        self.resize_env_hook = resize_env_hook
        self.shrink_target = shrink_target
        self.resize_grace_s = (self.grace_s if resize_grace_s is None
                               else float(resize_grace_s))
        self._resize_request: Optional[Tuple[int, str]] = None
        self._elastic_override = elastic
        self._procs_track_world = True
        # serializes worker-table mutation against the embedding
        # surface: a fleet's deploy thread (add_worker/retire/spawn)
        # runs concurrently with its sweep thread (supervise_once) —
        # run()'s single-threaded trainer loop never contends on it
        self._table_lock = core_locks.make_lock("Supervisor._table_lock")
        self._workers: Dict[int, _Worker] = {}  # guarded-by: self._table_lock
        self._telemetry = None
        self.report = SupervisorReport(policy=self.policy)

    # -- registration -----------------------------------------------------

    def add_worker(self, rank: int, cmd: List[str],
                   env: Optional[dict] = None,
                   log_path: Optional[str] = None, role: str = "trainer",
                   essential: bool = False,
                   max_restarts: Optional[int] = None) -> int:
        """Register a respawnable worker (spawned by :meth:`start` or
        :meth:`spawn_worker`). ``max_restarts`` overrides the
        supervisor-wide budget for this rank only (0 = never restart —
        the deploy-canary setting)."""
        with self._table_lock:
            if rank in self._workers:
                raise InvalidArgumentError(
                    f"rank {rank} already registered")
            self._workers[rank] = _Worker(rank, cmd, env, log_path,
                                          role, essential,
                                          max_restarts=max_restarts)
        return rank

    # -- telemetry (ISSUE 10) ----------------------------------------------

    def start_telemetry(self, port: Optional[int] = None):
        """Serve this pod's ``/metrics`` + ``/healthz``: the
        supervisor's own process registry (restart/resize/failure
        counters) followed by the merged snapshot of every worker's
        registry — workers publish their snapshots to per-rank files in
        the heartbeat dir (the env :meth:`_obs_worker_env` stamps), and
        the page folds them via :func:`~paddle1_tpu.obs.merge_snapshots`
        labeled ``scope="workers"``. ``port`` None reads the
        ``obs_port`` flag (0 keeps it off); 0 binds ephemeral. Returns
        the :class:`~paddle1_tpu.obs.TelemetryServer` (or None)."""
        if self._telemetry is not None:
            return self._telemetry
        from ..obs.http import TelemetryServer, resolve_port_flag
        port = resolve_port_flag(port)
        if port is None:
            return None
        self._telemetry = TelemetryServer(
            port=port, providers=[self._worker_metrics_page],
            healthz=self._healthz).start()
        return self._telemetry

    def stop_telemetry(self) -> None:
        if self._telemetry is not None:
            self._telemetry.stop()
            self._telemetry = None

    def _worker_snapshots(self) -> Dict[int, dict]:
        import json as _json
        out: Dict[int, dict] = {}
        with self._table_lock:
            ranks = list(self._workers)
        for rank in ranks:
            path = os.path.join(self._heartbeat_dir(),
                                f"metrics.{rank}.json")
            try:
                with open(path) as f:
                    out[rank] = _json.load(f)
            except (OSError, ValueError):
                continue  # not published yet / torn mid-replace (the
                # writer's atomic rename makes this a startup race only)
        return out

    def _worker_metrics_page(self) -> str:
        from ..obs.registry import merge_snapshots, render_snapshot_text
        snaps = self._worker_snapshots()
        if not snaps:
            return ""
        return render_snapshot_text(merge_snapshots(snaps.values()),
                                    namespace="p1t",
                                    label=("scope", "workers"))

    def _healthz(self) -> dict:
        with self._table_lock:
            workers = {
                w.rank: ("done" if w.done else
                         "running" if w.proc is not None
                         and w.proc.poll() is None else "down")
                for w in self._workers.values()}
        return {"ok": all(v != "down" for v in workers.values()),
                "policy": self.policy, "workers": workers,
                "restarts": dict(self.report.restarts),
                "resizes": len(self.report.resizes)}

    def attach(self, rank: int, proc, role: str = "trainer",
               essential: bool = False) -> int:
        """Adopt an already-running process (legacy watch surfaces /
        fleet mp workers via :class:`MpProcessHandle`). No respawn spec,
        no heartbeat: exit-only watching; ``restart`` falls back to
        ``fail_fast`` for these."""
        with self._table_lock:
            # under the lock like add_worker: the legacy watch surfaces
            # adopt from the training thread while an embedding owner's
            # sweep may already be iterating the table (the unlocked
            # check-then-insert here was the one _workers mutation the
            # guarded-by pass caught outside the lock)
            if rank in self._workers:
                raise InvalidArgumentError(
                    f"rank {rank} already registered")
            self._workers[rank] = _Worker(rank, role=role,
                                          essential=essential, proc=proc)
        return rank

    # -- spawning ---------------------------------------------------------

    def _heartbeat_dir(self) -> str:
        if self._hb_dir is None:
            self._hb_dir = self.log_dir or tempfile.mkdtemp(
                prefix="p1t_supervisor_")
        os.makedirs(self._hb_dir, exist_ok=True)
        return self._hb_dir

    def _spawn(self, w: _Worker) -> None:
        hb_dir = self._heartbeat_dir()
        w.hb_file = os.path.join(hb_dir, f"hb.{w.rank}")
        # the dump file is per-INCARNATION: a re-hung restart must not
        # read (or truncate — collected dumps stay intact in
        # report.stack_dumps) the previous life's traceback
        w.dump_path = os.path.join(
            hb_dir, f"stackdump.{w.rank}" +
            (f".r{w.incarnation}" if w.incarnation else ""))
        # fresh channel per incarnation: a stale beat/unhealthy marker/
        # dump left by a PREVIOUS RUN sharing this dir must not be read
        # as this one's
        with open(w.hb_file, "w"):
            pass
        with open(w.dump_path, "w"):
            pass
        w.hb_spawn_mtime = os.path.getmtime(w.hb_file)
        try:
            os.unlink(w.hb_file + UNHEALTHY_SUFFIX)
        except OSError:
            pass
        env = dict(w.env if w.env is not None else os.environ)
        env[HEARTBEAT_ENV] = w.hb_file
        env[STACKDUMP_ENV] = w.dump_path
        env[INCARNATION_ENV] = str(w.incarnation)
        self._obs_worker_env(w, env)
        self._collective_worker_env(env)
        stdout = None
        if w.log_path:
            if w.log_fh is not None:  # previous incarnation's handle
                try:
                    w.log_fh.close()
                except OSError:  # pragma: no cover
                    pass
            os.makedirs(os.path.dirname(w.log_path) or ".", exist_ok=True)
            # incarnation 0 truncates (a re-run with the same log_dir
            # must not concatenate onto the previous run, matching the
            # unsupervised spawn); restarts within THIS supervisor's
            # lifetime append so the first life's tail survives
            w.log_fh = open(w.log_path, "a" if w.incarnation else "w")
            if w.incarnation:
                w.log_fh.write(f"\n--- supervisor restart "
                               f"#{w.incarnation} ---\n")
                w.log_fh.flush()
            stdout = w.log_fh
        w.proc = subprocess.Popen(
            w.cmd, env=env, stdout=stdout,
            stderr=subprocess.STDOUT if stdout else None)

    def _obs_worker_env(self, w: _Worker, env: Dict[str, str]) -> None:
        """Stamp observability plumbing into one worker's env (ISSUE
        10): the trace sink + events journal flags (so `set_flags` in
        the supervisor process reaches children that only inherit
        env), the job's trace context (worker spans join the
        supervisor's trace), and a per-rank snapshot file the worker's
        process registry publishes to — what :meth:`start_telemetry`
        aggregates. Explicit worker env always wins."""
        from ..obs import registry as obs_registry
        from ..obs import trace as obs_trace
        for flag_name in ("obs_trace_dir", "obs_events_file"):
            v = core_flags.flag(flag_name)
            key = "FLAGS_" + flag_name
            if v and key not in env:
                env[key] = str(v)
        if core_flags.flag("obs_metrics"):
            env.setdefault("FLAGS_obs_metrics", "1")
            env.setdefault(
                obs_registry.SNAPSHOT_ENV,
                os.path.join(self._heartbeat_dir(),
                             f"metrics.{w.rank}.json"))
        entry = obs_trace.env_entry()
        if entry is not None and entry[0] not in env:
            env[entry[0]] = entry[1]

    def _collective_worker_env(self, env: Dict[str, str]) -> None:
        """Stamp the collective-schedule sanitizer into one worker's
        env (ISSUE 14): the flag (so ``set_flags`` in the supervisor
        process reaches env-only children) and the per-job journal dir
        the sweep-time verifier reads. The dir rides its own
        ``PADDLE_COLLECTIVE_JOURNAL`` env, which the worker's
        sanitizer CONSUMES at arm time — grandchildren (loader worker
        processes) must never journal onto the rank's file (the PR 3
        heartbeat-env lesson). Explicit worker env always wins."""
        if not core_flags.flag("debug_collective_sanitizer"):
            return
        from ..core import collective_sanitizer as csan
        env.setdefault("FLAGS_debug_collective_sanitizer", "1")
        env.setdefault(csan.JOURNAL_ENV, self._collective_journal_dir())

    def _collective_journal_dir(self) -> str:
        """The journal dir this job's workers write and the sweep
        verifier reads: the ``collective_journal_dir`` flag, or a
        ``collective/`` subdir of the heartbeat dir."""
        d = core_flags.flag("collective_journal_dir") or os.path.join(
            self._heartbeat_dir(), "collective")
        os.makedirs(d, exist_ok=True)
        return d

    def start(self) -> "Supervisor":
        """Spawn every registered (not yet running) respawnable worker."""
        for w in self._workers.values():
            if w.proc is None:
                if not w.respawnable:
                    raise InvalidArgumentError(
                        f"rank {w.rank} has neither a command nor a "
                        "process")
                self._spawn(w)
        return self

    # -- detection --------------------------------------------------------

    def _classify(self, w: _Worker) -> Optional[WorkerFailure]:
        """One poll of one worker; None when healthy (or already done)."""
        if w.done or w.abandoned or w.proc is None:
            return None
        ret = w.proc.poll()
        if ret is not None:
            if ret == 0 and not w.essential:
                w.done = True
                return None
            # an essential worker (PS server) exiting AT ALL while the
            # job runs strands everyone — treat clean exit as failure
            code = ret if ret != 0 else 1
            return WorkerFailure(w.rank, EXIT, exit_code=code,
                                 reason=f"exit code {ret}", raw_exit=ret)
        if w.hb_file is not None:
            unhealthy = w.hb_file + UNHEALTHY_SUFFIX
            if os.path.exists(unhealthy):
                try:
                    with open(unhealthy) as f:
                        reason = f.read().strip()
                except OSError:
                    reason = ""
                return WorkerFailure(w.rank, UNHEALTHY,
                                     reason=reason or "unhealthy report")
            try:
                mtime = os.path.getmtime(w.hb_file)
            except OSError:
                mtime = w.hb_spawn_mtime or 0.0
            age = time.time() - mtime
            first_beat_pending = (w.hb_spawn_mtime is not None
                                  and mtime <= w.hb_spawn_mtime)
            limit = (max(self.startup_grace_s, self.hang_timeout)
                     if first_beat_pending else self.hang_timeout)
            if age > limit:
                return WorkerFailure(
                    w.rank, HANG,
                    reason=f"heartbeat {age:.1f}s old (> {limit:.1f}s)")
        return None

    def check_failed(self) -> List[WorkerFailure]:
        """One detection sweep with NO policy action — the embedding
        surface ``fleet.ProcessMultiTrainer`` polls between queue
        timeouts to catch workers that died without reporting."""
        out = []
        for w in self._workers.values():
            f = self._classify(w)
            if f is not None:
                out.append(f)
        return out

    # -- embedding surface (non-trainer worker pools) ---------------------

    def supervise_once(self) -> List[SupervisionEvent]:
        """One detection **and response** sweep for an embedding caller
        (a serving fleet supervising long-lived replicas): classify
        every worker, record each failure (hang stack dumps, unhealthy
        markers — the trainer path's bookkeeping), apply the per-rank
        ``restart`` policy where it applies, and return what happened.
        Unlike :meth:`run` this never terminates the pod: a permanent
        failure is an event (``restart_exhausted`` / ``detected``), and
        the owner decides what it means. Clean exits of non-essential
        workers just mark the rank done (see :meth:`worker_done`)."""
        with self._table_lock:
            workers = list(self._workers.values())
        events = []
        for w in workers:
            f = self._classify(w)
            if f is None:
                continue
            self._record_failure(w, f)
            if self.policy == "restart" and w.respawnable \
                    and not w.essential:
                if self._restart_worker(w):
                    events.append(SupervisionEvent(f, "restarted"))
                    continue
                # out of budget: a wedged-but-alive process (hang /
                # unhealthy) must still be put down — the owner's
                # replacement decision starts from a dead rank, and a
                # half-alive one would keep holding its sockets
                self._kill_worker(w, signal.SIGKILL)
                w.abandoned = True
                events.append(SupervisionEvent(f, "restart_exhausted"))
            else:
                if f.kind == EXIT:
                    w.abandoned = True  # the corpse re-reports otherwise
                events.append(SupervisionEvent(f, "detected"))
        return events

    def _get_worker(self, rank: int) -> _Worker:
        """Typed lookup for the embedding accessors: an unknown (e.g.
        already-retired) rank raises the module's InvalidArgumentError,
        not a raw KeyError — a fleet sweep racing a deploy's retire()
        must get a catchable, documented condition."""
        with self._table_lock:
            w = self._workers.get(rank)
        if w is None:
            raise InvalidArgumentError(
                f"rank {rank} is not registered (retired, or never "
                "added)")
        return w

    def spawn_worker(self, rank: int) -> None:
        """Spawn one registered, not-yet-running worker (a deploy adds
        a replica mid-flight and must not touch the rest of the pod the
        way :meth:`start` would)."""
        w = self._get_worker(rank)
        if w.proc is not None and w.proc.poll() is None:
            raise InvalidArgumentError(f"rank {rank} is already running")
        if not w.respawnable:
            raise InvalidArgumentError(
                f"rank {rank} has no command to spawn")
        self._spawn(w)

    def restart_rank(self, rank: int) -> bool:
        """Kill + relaunch one rank within its budget (the embedding
        owner's explicit lever — e.g. a fleet whose circuit breaker
        tripped on a replica the heartbeat still calls healthy). False
        when out of budget or not respawnable."""
        return self._restart_worker(self._get_worker(rank))

    def retire(self, rank: int,
               grace_s: Optional[float] = None) -> Optional[int]:
        """Deregister one rank for good: SIGTERM (the graceful-drain
        signal), bounded wait, SIGKILL stragglers, close its log.
        Returns the exit code (None if it never ran). A hot-swap
        retires the old replica after the new one took its place — the
        exit must NOT count as a failure, so the worker leaves the
        table before any sweep can classify it."""
        with self._table_lock:
            w = self._workers.pop(rank, None)
        if w is None:
            return None
        # a sweep that snapshotted the table BEFORE the pop still holds
        # this object: abandon it first so a straggler SIGKILL during
        # the grace window below can't be classified as a failure and
        # respawned into an untracked zombie
        w.abandoned = True
        rc = None
        if w.proc is not None:
            if w.proc.poll() is None:
                self._graceful_stop(
                    [w], self.grace_s if grace_s is None else grace_s,
                    straggler_note="did not drain on retire — SIGKILL")
            rc = w.proc.poll()
        if w.log_fh is not None:
            try:
                w.log_fh.close()
            except OSError:  # pragma: no cover
                pass
            w.log_fh = None
        return rc

    def kill_worker(self, rank: int) -> None:
        """SIGKILL one rank and abandon it (no relaunch, no further
        classification) — the embedding owner's terminal put-down for
        a wedged-but-alive replica whose restart budget is spent (a
        half-alive process would keep holding its port, memory, and
        heartbeat file). No-op for an already-retired rank."""
        with self._table_lock:
            w = self._workers.get(rank)
        if w is None:
            return
        w.abandoned = True
        self._kill_worker(w, signal.SIGKILL)

    def incarnation(self, rank: int) -> int:
        return self._get_worker(rank).incarnation

    def restarts_used(self, rank: int) -> int:
        return self.report.restarts.get(rank, 0)

    def set_restart_budget(self, rank: int,
                           max_restarts: Optional[int]) -> None:
        """Adjust one rank's restart-budget override (None restores the
        supervisor-wide budget) — a canary promoted into rotation earns
        the standing fleet's budget."""
        self._get_worker(rank).max_restarts = max_restarts

    def worker_done(self, rank: int) -> bool:
        """Whether the rank exited 0 (role-complete)."""
        w = self._workers.get(rank)
        return bool(w is not None and w.done)

    def worker_ranks(self) -> List[int]:
        return sorted(self._workers)

    # -- actions ----------------------------------------------------------

    def _collect_stack_dump(self, w: _Worker) -> Optional[str]:
        """SIGABRT the stuck worker and wait for its faulthandler
        (``health`` enables it on the per-rank dump file) to write the
        all-threads traceback; returns the dump path when something
        arrived. faulthandler's abort handler dumps and then dies, so
        keep looking briefly after the worker exits — the dump usually
        lands just before the death is observable."""
        if w.proc is None or w.dump_path is None:
            return None
        try:
            w.proc.send_signal(signal.SIGABRT)
        except (OSError, ValueError):
            return None
        deadline = time.monotonic() + self.dump_wait_s
        dead_since = None
        while time.monotonic() < deadline:
            try:
                if os.path.getsize(w.dump_path) > 0:
                    # one extra beat lets a mid-write dump finish
                    time.sleep(0.1)
                    self.report.stack_dumps.append(w.dump_path)
                    return w.dump_path
            except OSError:
                pass
            if w.proc.poll() is not None:
                # dead with no dump: wait a moment for the filesystem,
                # then give up (no faulthandler was enabled)
                if dead_since is None:
                    dead_since = time.monotonic()
                elif time.monotonic() - dead_since > 0.5:
                    break
            time.sleep(0.05)
        return None

    def _kill_worker(self, w: _Worker, sig=signal.SIGKILL) -> None:
        if w.proc is None or w.proc.poll() is not None:
            return
        try:
            w.proc.send_signal(sig)
        except (OSError, ValueError):  # pragma: no cover - already gone
            pass

    def _terminate_all(self) -> None:
        """Reference terminate_local_procs semantics over the pod:
        SIGTERM, bounded wait, SIGKILL stragglers."""
        alive = [w for w in self._workers.values()
                 if w.proc is not None and w.proc.poll() is None]
        for w in alive:
            self._kill_worker(w, signal.SIGTERM)
        deadline = time.monotonic() + self.grace_s
        for w in alive:
            try:
                w.proc.wait(timeout=max(0.1,
                                        deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                pass
            if w.proc.poll() is None:
                self._kill_worker(w, signal.SIGKILL)
                try:
                    w.proc.wait(timeout=5)
                except subprocess.TimeoutExpired:  # pragma: no cover
                    pass
        self._close_logs()

    def _close_logs(self) -> None:
        for w in self._workers.values():
            if w.log_fh is not None:
                try:
                    w.log_fh.close()
                except OSError:  # pragma: no cover
                    pass
                w.log_fh = None

    def _restart_worker(self, w: _Worker) -> bool:
        """Kill + relaunch one rank (same cmd/env, incarnation+1).
        False when the rank is out of restart budget or not
        respawnable."""
        used = self.report.restarts.get(w.rank, 0)
        budget = (self.max_restarts if w.max_restarts is None
                  else w.max_restarts)
        if not w.respawnable or used >= budget:
            return False
        self._kill_worker(w, signal.SIGKILL)
        try:
            w.proc.wait(timeout=self.grace_s)
        except subprocess.TimeoutExpired:  # pragma: no cover
            pass
        w.incarnation += 1
        self.report.restarts[w.rank] = used + 1
        self._spawn(w)
        from ..obs import events as obs_events
        from ..obs import registry as obs_registry
        obs_registry.process_registry().counter(
            "ft_worker_restarts_total").inc()
        obs_events.emit("worker_restart", rank=w.rank, role=w.role,
                        incarnation=w.incarnation,
                        restarts_used=used + 1)
        print(f"supervisor: rank {w.rank} relaunched "
              f"(restart {used + 1}/{budget}, "
              f"incarnation {w.incarnation})", file=sys.stderr)
        return True

    def _graceful_stop(self, workers, grace_s: float,
                       straggler_note: str = "",
                       kill_stragglers: bool = True) -> None:
        """The shared drain primitive (policy ``drain`` and elastic
        resize): SIGTERM → bounded wait → optionally SIGKILL
        stragglers. The SIGTERM side is what lets a ResilientTrainer
        commit its final checkpoint; a SIGKILLed straggler resumes from
        its last periodic commit instead (atomic manifests make that
        safe). ``kill_stragglers=False`` leaves stragglers to the
        caller (policy drain hands them to ``_terminate_all``, whose
        own TERM-grace-KILL ladder gives them a second window)."""
        workers = [w for w in workers
                   if w.proc is not None and w.proc.poll() is None]
        for w in workers:
            self._kill_worker(w, signal.SIGTERM)
        deadline = time.monotonic() + grace_s
        while time.monotonic() < deadline:
            if all(w.proc.poll() is not None for w in workers):
                break
            time.sleep(min(self.poll_s, 0.2))
        if not kill_stragglers:
            return
        for w in workers:
            if w.proc.poll() is None:
                if straggler_note:
                    print(f"supervisor: rank {w.rank} {straggler_note}",
                          file=sys.stderr)
                self._kill_worker(w, signal.SIGKILL)
                try:
                    w.proc.wait(timeout=5)
                except subprocess.TimeoutExpired:  # pragma: no cover
                    pass

    def _drain_all(self, grace_s: Optional[float] = None) -> None:
        """Graceful pod stop: SIGTERM every live worker (the health
        SIGTERM handler turns it into chaos.request_preemption + drain,
        so resilient loops checkpoint and exit), wait out the grace
        window, then terminate stragglers."""
        self.report.drained = True
        from ..obs import events as obs_events
        obs_events.emit("drain", workers=len(self._workers))
        self._graceful_stop(list(self._workers.values()),
                            self.grace_s if grace_s is None else grace_s,
                            kill_stragglers=False)
        self._terminate_all()

    # -- elastic world-resize ---------------------------------------------

    def _trainers(self) -> List[_Worker]:
        return [w for w in self._workers.values() if not w.essential]

    def _elastic_workers(self) -> List[_Worker]:
        """The ranks a resize may drain/relaunch: respawnable trainers."""
        return [w for w in self._trainers() if w.respawnable]

    def _elastic_routing(self) -> bool:
        """Whether failures route into the resize path: explicit
        ``resize`` policy, or ``restart`` in a multi-worker world (a
        dead rank cannot rejoin live collectives — relaunching it alone
        would strand its peers, the PR 3 dead end). The ``elastic=False``
        override wins: a per-node supervisor of a multi-NODE pod owns
        only its slice of the global ranks and must never resize."""
        if self._elastic_override is False:
            return False
        if self.policy == "resize":
            return True
        return self.policy == "restart" and \
            (self.world_size or 0) > 1 and len(self._elastic_workers()) > 1

    def _check_resize(self, new_world: int) -> Optional[ResizeRefused]:
        """The two polite-refusal limits, as a typed result (shared by
        the synchronous :meth:`request_resize` pre-check and the
        sweep-time non-strict ``_resize`` path so the reasons can
        never drift apart)."""
        floor = max(1, self.min_world)
        if new_world < floor:
            return ResizeRefused(
                reason=RESIZE_BELOW_FLOOR, requested=new_world,
                limit=floor,
                detail="raise ft_elastic_min_world or ask for more")
        if len(self.report.resizes) >= self.max_resizes:
            return ResizeRefused(
                reason=RESIZE_BUDGET_EXHAUSTED, requested=new_world,
                limit=self.max_resizes,
                detail="membership-churn budget ft_max_resizes spent")
        return None

    def _record_refusal(self, refusal: ResizeRefused) -> None:
        """Count + journal one typed refusal (both refusal surfaces)."""
        self.report.resize_refusals.append(
            {"requested": refusal.requested, "reason": refusal.reason,
             "limit": refusal.limit})
        from ..obs import events as obs_events
        from ..obs import registry as obs_registry
        obs_registry.process_registry().counter(
            "ft_resize_refusals_total").inc()
        obs_registry.process_registry().counter(
            f"ft_resize_refused_{refusal.reason}_total").inc()
        obs_events.emit("resize_refused", requested=refusal.requested,
                        reason=refusal.reason, limit=refusal.limit)

    def request_resize(self, new_world: int, reason: str = "requested"
                       ) -> Optional[ResizeRefused]:
        """Ask the supervision loop to resize the world at its next
        sweep (thread-safe: callable from another thread, e.g. a
        cluster-capacity watcher that just got preemption notices or
        freed machines back, or an :class:`serving.Autoscaler`).
        Growth and shrink both route through the same drain →
        recompute-mesh → reshard → relaunch path.

        Returns ``None`` when the request was accepted for the next
        sweep, or a typed :class:`ResizeRefused` when it is already
        known to be refusable (below the world floor, or the resize
        budget is spent) — counted in ``ft_resize_refusals_total`` and
        journaled, so a scaling controller can distinguish "asked for
        too little" from "the world is out of churn budget" and back
        off instead of flapping. A request that passes the pre-check
        can still be refused at sweep time if the budget is consumed
        by a failure-driven resize in between (same typed accounting)."""
        if int(new_world) < 1:
            raise InvalidArgumentError(
                f"cannot resize to world size {new_world}")
        if self.world_size is not None \
                and int(new_world) == self.world_size:
            return None  # no-op request: never refusable, never queued
        refusal = self._check_resize(int(new_world))
        if refusal is not None:
            print(f"supervisor: {refusal}", file=sys.stderr)
            self._record_refusal(refusal)
            return refusal
        self._resize_request = (int(new_world), reason)
        return None

    def _record_failure(self, w: _Worker, f: WorkerFailure) -> None:
        """Bookkeeping common to policy handling and resize routing:
        counters, stack dump for hangs, marker consumption."""
        self.report.failures.append(f)
        from ..obs import events as obs_events
        from ..obs import registry as obs_registry
        m = obs_registry.process_registry()
        m.counter("ft_worker_failures_total").inc()
        if f.kind == HANG:
            m.counter("ft_worker_hangs_total").inc()
        obs_events.emit("worker_failure", rank=w.rank, role=w.role,
                        kind=f.kind, reason=f.reason,
                        exit_code=f.exit_code)
        if f.kind == HANG:
            self.report.hangs_detected += 1
            f.stack_dump = self._collect_stack_dump(w)
            dump = (f" (stack dump: {f.stack_dump})"
                    if f.stack_dump else "")
            print(f"supervisor: rank {w.rank} HUNG — {f.reason}{dump}",
                  file=sys.stderr)
        elif f.kind == UNHEALTHY:
            self.report.unhealthy_reports += 1
            # consume the marker so a handled report doesn't re-fire
            try:
                os.unlink(w.hb_file + UNHEALTHY_SUFFIX)
            except OSError:
                pass
            print(f"supervisor: rank {w.rank} reported unhealthy — "
                  f"{f.reason}", file=sys.stderr)
        else:
            print(f"supervisor: rank {w.rank} failed — {f.reason}",
                  file=sys.stderr)

    def _clone_worker(self, template: _Worker, rank: int) -> _Worker:
        """A grow beyond the registered fleet clones the lowest-rank
        spec; world coordinates are overlaid at spawn. Incarnation
        starts at 1 via the respawn loop's bump, so rank-qualified
        chaos (incarnation 0 only) can never fire in a grown rank."""
        log_path = template.log_path
        if log_path:
            import re as _re
            log_path = _re.sub(rf"\.{template.rank}(?=$|\.log$)",
                               f".{rank}", log_path)
        return _Worker(rank, template.cmd, template.base_env, log_path,
                       template.role, template.essential)

    def _resize(self, new_world: int, reason: str,
                failed: Tuple[_Worker, ...] = (),
                fail_code: int = 1, strict: bool = True) -> Optional[int]:
        """Drain → recompute → relaunch the fleet at ``new_world``.
        Returns None when the resize succeeded (the loop continues) or
        the pod exit code when it cannot (below the floor / out of
        budget): elasticity has limits, and hitting one after losing a
        rank is a failed job, not an infinite shrink. ``failed`` ranks
        (already dead or wedged) are hard-killed, never drained;
        ``fail_code`` is the pod exit code when a strict resize is
        refused. ``strict=False`` (explicit requests on a HEALTHY
        world) refuses politely instead of killing the job."""
        old_world = self.world_size or len(self._elastic_workers())
        new_world = int(new_world)
        if new_world == old_world and not failed:
            return None  # no-op request
        refusal = self._check_resize(new_world)
        if refusal is not None:
            print(f"supervisor: {refusal} — "
                  + ("failing the pod" if strict else "request refused"),
                  file=sys.stderr)
            if not strict:
                self._record_refusal(refusal)
                return None
            self._terminate_all()
            return fail_code
        print(f"supervisor: resizing world {old_world} -> {new_world} "
              f"({reason})", file=sys.stderr)
        # 1. put down the failed ranks (dead or wedged — never drained)
        for w in failed:
            self._kill_worker(w, signal.SIGKILL)
            if w.proc is not None:
                try:
                    w.proc.wait(timeout=self.grace_s)
                except subprocess.TimeoutExpired:  # pragma: no cover
                    pass
        # 2. drain survivors: SIGTERM → ResilientTrainer commits a
        # final checkpoint (manifest carries the mesh descriptor) and
        # exits; stragglers resume from their last periodic commit
        failed_ids = {id(w) for w in failed}
        self._graceful_stop(
            [w for w in self._elastic_workers()
             if id(w) not in failed_ids],
            self.resize_grace_s,
            straggler_note=(f"did not drain within "
                            f"{self.resize_grace_s:.0f}s — SIGKILL "
                            "(resumes from its last periodic "
                            "checkpoint)"))
        # 3. recompute the worker table for the new world
        elastic = sorted(self._elastic_workers(), key=lambda w: w.rank)
        if self._procs_track_world:
            template = elastic[0]
            with self._table_lock:
                for w in elastic:
                    if w.rank >= new_world:
                        if w.log_fh is not None:
                            try:
                                w.log_fh.close()
                            except OSError:  # pragma: no cover
                                pass
                            w.log_fh = None
                        del self._workers[w.rank]
                for rank in range(new_world):
                    if rank not in self._workers:
                        self._workers[rank] = self._clone_worker(
                            template, rank)
                targets = [self._workers[r] for r in range(new_world)]
        else:
            targets = elastic  # single-controller: env-only resize
        # 4. relaunch with the new world coordinates
        self.report.resizes.append({"from": old_world, "to": new_world,
                                    "reason": reason})
        from ..obs import events as obs_events
        from ..obs import registry as obs_registry
        obs_registry.process_registry().counter("ft_resizes_total").inc()
        obs_events.emit("resize", world_from=old_world,
                        world_to=new_world, reason=reason)
        self.report.world_size = new_world
        self.world_size = new_world
        for w in targets:
            w.done = False
            w.incarnation += 1
            env = dict(w.base_env if w.base_env is not None
                       else os.environ)
            env["PADDLE_ELASTIC_WORLD"] = str(new_world)
            if self._procs_track_world:
                env["PADDLE_TRAINER_ID"] = str(w.rank)
                env["PADDLE_TRAINERS_NUM"] = str(new_world)
            if self.resize_env_hook is not None:
                env.update({str(k): str(v) for k, v in
                            (self.resize_env_hook(w.rank, new_world)
                             or {}).items()})
            w.env = env
            self._spawn(w)
        return None

    # -- the loop ---------------------------------------------------------

    def _on_failure(self, w: _Worker, f: WorkerFailure) -> Optional[int]:
        """Policy dispatch for one detected failure. Returns the pod
        exit code when the failure ends the job, None when handled.
        Resize-eligible failures never reach here — the run loop routes
        them into :meth:`_resize` as a batch."""
        self._record_failure(w, f)

        if self.policy == "restart":
            if self._restart_worker(w):
                return None
            print(f"supervisor: rank {w.rank} out of restart budget "
                  f"({self.max_restarts}) — failing the pod",
                  file=sys.stderr)
        elif self.policy == "drain":
            self._drain_all()
            # a drain triggered by a crash is still a failed job; one
            # triggered by hang/unhealthy stopped gracefully with the
            # state checkpointed
            return f.exit_code if f.kind == EXIT else 0
        # fail_fast (and restart fallthrough): kill the pod
        self._terminate_all()
        return f.exit_code if f.exit_code is not None else 1

    def _poll_collective_schedules(self, watcher,
                                   final: bool = False) -> None:
        """One sweep of the cross-rank collective-schedule verifier
        (``final=True`` at clean job completion adds the completion
        check: a rank whose schedule simply STOPS short of its peers'
        — the canonical skipped-last-collective deadlock — must not
        pass as success). On divergence: kill the pod (the ranks are
        headed for a deadlock — on hardware they would already be
        blocked), record the evidence on the report, and re-raise the
        typed error."""
        from ..core.collective_sanitizer import CollectiveDivergenceError
        try:
            if final:
                watcher.final()
            else:
                watcher.poll()
        except CollectiveDivergenceError as e:
            self.report.collective_divergence = str(e)
            print(f"supervisor: collective-schedule divergence — "
                  f"failing the pod\n{e}", file=sys.stderr)
            self._terminate_all()
            self.report.exit_code = 1
            raise

    def run(self) -> int:
        """Supervise until the job completes (every non-essential worker
        exited 0 — essential workers, e.g. PS servers, are then torn
        down) or a failure ends it per policy. Returns the pod exit
        code. KeyboardInterrupt kills the pod and re-raises (the
        reference watch contract). With ``debug_collective_sanitizer``
        on, every sweep also cross-checks the workers' collective
        journals and raises the typed ``CollectiveDivergenceError``
        (pod torn down, evidence on ``report.collective_divergence``)
        when two ranks' schedules disagree."""
        self.start()
        if not self._trainers():
            # essential=True means "must outlive the trainers"; with no
            # trainers there is nothing to outlive (a server-only node
            # watches its servers as plain workers instead)
            raise InvalidArgumentError(
                "Supervisor.run needs at least one non-essential worker")
        if self.world_size is None:
            self.world_size = len(self._elastic_workers()) or \
                len(self._trainers())
        self.report.world_size = self.world_size
        # one process per rank (resize scales the process count) vs a
        # single-controller fleet (resize rewrites world coordinates)
        self._procs_track_world = (
            len(self._elastic_workers()) == self.world_size)
        # collective-schedule verifier (ISSUE 14): when the sanitizer
        # flag is on, every sweep cross-checks the per-rank journals —
        # a diverging schedule (the would-be multi-host deadlock)
        # fails the pod typed while the ranks are still heartbeating
        watcher = None
        if core_flags.flag("debug_collective_sanitizer"):
            from ..core.collective_sanitizer import JournalWatcher
            watcher = JournalWatcher(self._collective_journal_dir())
        try:
            while True:
                if watcher is not None:
                    self._poll_collective_schedules(watcher)
                sweep = []
                for w in list(self._workers.values()):
                    f = self._classify(w)
                    if f is not None:
                        sweep.append((w, f))
                if all(w.done for w in self._trainers()) and all(
                        w.essential and f.kind == EXIT and f.raw_exit == 0
                        for w, f in sweep):
                    # job complete — an essential worker (PS server)
                    # that exited CLEANLY in the same sweep the last
                    # trainer finished is a success, not a strand (the
                    # legacy watch_ps_procs ordering). Checked BEFORE
                    # any pending resize request: a grow racing the
                    # last trainer's exit must not respawn a finished
                    # fleet
                    if watcher is not None and not (
                            self.report.failures
                            or self.report.resizes):
                        # clean completion: every rank must claim the
                        # SAME complete schedule — a strict-prefix
                        # journal (one rank skipped its last
                        # collective) is the deadlock shape, not a
                        # success. Skipped after failures/resizes: a
                        # killed rank's epoch legitimately ends early
                        self._poll_collective_schedules(watcher,
                                                        final=True)
                    self._terminate_all()  # tear down essential workers
                    self.report.exit_code = 0
                    return 0
                if self._resize_request is not None:
                    req, self._resize_request = self._resize_request, None
                    # strict=False: a refused operator request (floor/
                    # budget) is logged, never fatal to a healthy pod
                    self._resize(req[0], req[1], strict=False)
                    continue  # re-sweep the fresh fleet
                if self._elastic_routing():
                    # membership change: handle every resize-eligible
                    # failure of this sweep as ONE shrink (preempting 2
                    # of 8 hosts is one event, not two relaunch cycles)
                    eligible = [(w, f) for w, f in sweep
                                if w.respawnable and not w.essential]
                    rest = [(w, f) for w, f in sweep
                            if not (w.respawnable and not w.essential)]
                    for w, f in rest:
                        rc = self._on_failure(w, f)
                        if rc is not None:
                            self.report.exit_code = rc
                            return rc
                    if eligible:
                        for w, f in eligible:
                            self._record_failure(w, f)
                        fails = [f for _, f in eligible]
                        if self.shrink_target is not None:
                            target = int(self.shrink_target(
                                self.world_size, fails))
                        else:
                            target = self.world_size - len(eligible)
                        code = next((f.exit_code for f in fails
                                     if f.exit_code), 1)
                        rc = self._resize(
                            target,
                            f"worker loss ({[f.rank for f in fails]})",
                            failed=tuple(w for w, _ in eligible),
                            fail_code=code)
                        if rc is not None:
                            self.report.exit_code = rc
                            return rc
                else:
                    for w, f in sweep:
                        rc = self._on_failure(w, f)
                        if rc is not None:
                            self.report.exit_code = rc
                            return rc
                time.sleep(self.poll_s)
        except KeyboardInterrupt:
            self._terminate_all()
            raise
        finally:
            self.stop_telemetry()
            self._close_logs()
