"""Meta-optimizer composition (reference
python/paddle/distributed/fleet/meta_optimizers/ + strategy_compiler.py).

The reference rewrites ProgramDescs per strategy; the TPU build compiles the
strategy into **ParallelEngine configuration** (mesh degrees, ZeRO stage,
grad accumulation, clipping, AMP dtype) — one jit, GSPMD inserts the
collectives. ``compile_strategy`` is that mapping; LocalSGD and DGC, which
change the *update rule* rather than the sharding, are optimizer wrappers.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

from ...core.tensor import Tensor
from .strategy import DistributedStrategy

__all__ = ["compile_strategy", "LocalSGDOptimizer", "DGCMomentumOptimizer"]


def compile_strategy(strategy: DistributedStrategy,
                     n_devices: Optional[int] = None) -> Dict[str, Any]:
    """DistributedStrategy → ParallelEngine kwargs (the StrategyCompiler
    analog, reference fleet_base.py:1293/strategy_compiler.py).

    Mapping table (reference meta-optimizer → TPU mechanism):
      sharding            → zero_stage over the 'sharding' mesh axis
      gradient_merge      → grad_accum micro-batching
      tensor_parallel /
      hybrid_configs      → mesh degrees (mp/pp/dp/sharding)
      recompute           → jax.checkpoint in the model (flag surfaced)
      amp                 → bf16 autocast inside the step
      dgc / localsgd      → optimizer wrappers (see below)
      fuse_allreduce etc. → no-ops: XLA already fuses/schedules comm
    """
    import jax

    from ...core.errors import InvalidArgumentError
    conf = strategy.to_dict()
    n = n_devices if n_devices is not None else len(jax.devices())
    hybrid = conf.get("hybrid_configs", {}) or {}
    mp = int(hybrid.get("mp_degree", 1))
    pp = int(hybrid.get("pp_degree", 1))
    dp_requested = int(hybrid.get("dp_degree", 1))
    zero_stage = 0
    sharding_requested = 1
    if conf.get("sharding"):
        sc = conf.get("sharding_configs", {}) or {}
        zero_stage = int(sc.get("stage", 2))
        sharding_requested = int(sc.get("sharding_degree", 1))
    if conf.get("tensor_parallel"):
        tc = conf.get("tensor_parallel_configs", {}) or {}
        mp = max(mp, int(tc.get("tensor_parallel_degree", 1)))
    if n % (mp * pp) != 0:
        raise InvalidArgumentError(
            f"hybrid_configs mp_degree={mp} * pp_degree={pp} does not "
            f"divide the device count {n}")
    # one elastic axis absorbs the remainder: the axis the user did NOT
    # pin. With sharding on and no explicit degree, sharding absorbs it
    # (respecting an explicit dp); otherwise dp absorbs it.
    dp = dp_requested
    sharding = sharding_requested
    fixed = mp * pp
    if dp * sharding * fixed != n:
        if zero_stage and sharding_requested <= 1:
            if n % (fixed * dp) != 0:
                raise InvalidArgumentError(
                    f"dp_degree={dp} * mp*pp={fixed} does not divide "
                    f"device count {n}")
            sharding = n // (fixed * dp)
        else:
            if n % (fixed * sharding) != 0:
                raise InvalidArgumentError(
                    f"sharding_degree={sharding} * mp*pp={fixed} does not "
                    f"divide device count {n}")
            dp = n // (fixed * sharding)
    degrees = {"dp": dp, "mp": mp, "pp": pp, "sharding": max(sharding, 1)}

    grad_accum = 1
    if conf.get("gradient_merge"):
        gm = conf.get("gradient_merge_configs", {}) or {}
        grad_accum = int(gm.get("k_steps", 1))

    # fp16 autocast maps to bf16 on TPU regardless of pure/mixed mode
    # (bf16 needs no loss scaling — the GradScaler machinery stays for
    # API compat but the engine path is scale-free)
    amp_dtype = "bfloat16" if conf.get("amp") else None

    pp_microbatches = None
    if pp > 1 or conf.get("pipeline"):
        pc = conf.get("pipeline_configs", {}) or {}
        pp_microbatches = int(pc.get("accumulate_steps", 0)) or None

    return {"degrees": degrees, "zero_stage": zero_stage,
            "grad_accum": grad_accum,
            "amp_dtype": amp_dtype,
            "pp_microbatches": pp_microbatches,
            "recompute": bool(conf.get("recompute")),
            "train_steps_per_sync": max(
                int(conf.get("train_steps_per_sync", 1)), 1)}


def apply_optimizer_meta(optimizer, strategy: DistributedStrategy):
    """The lars/lamb meta-optimizer rewrites (reference
    meta_optimizers/lars_optimizer.py, lamb_optimizer.py): with
    ``strategy.lars`` a plain Momentum optimizer is swapped for LARS
    (and Adam for Lamb under ``strategy.lamb``), keeping lr/momentum/
    parameter list. Other optimizer types pass through unchanged, as
    the reference's can_apply gate does."""
    from ...optimizer import Adam, Lamb, Lars, Momentum
    conf = strategy.to_dict()
    if conf.get("lars") and type(optimizer) is Momentum:
        lc = conf.get("lars_configs", {}) or {}
        return Lars(learning_rate=optimizer._learning_rate,
                    momentum=optimizer._momentum,
                    parameters=optimizer._parameter_list,
                    lars_coeff=float(lc.get("lars_coeff", 0.001)),
                    lars_weight_decay=float(
                        lc.get("lars_weight_decay", 0.0005)),
                    epsilon=float(lc.get("epsilon", 1e-9)),
                    # carry the user's regularization through the swap
                    # (reference lars meta-opt passes regularization=)
                    weight_decay=optimizer._weight_decay or None,
                    grad_clip=optimizer._grad_clip)
    if conf.get("lamb") and type(optimizer) is Adam:
        lc = conf.get("lamb_configs", {}) or {}
        # LAMB's decay is its own decoupled term: an Adam weight_decay
        # becomes the lamb_weight_decay unless lamb_configs overrides
        wd = lc.get("lamb_weight_decay",
                    optimizer._weight_decay or 0.01)
        return Lamb(learning_rate=optimizer._learning_rate,
                    beta1=optimizer._beta1, beta2=optimizer._beta2,
                    epsilon=optimizer._epsilon,
                    parameters=optimizer._parameter_list,
                    lamb_weight_decay=float(wd),
                    grad_clip=optimizer._grad_clip)
    return optimizer


class _WrappedOptimizer:
    """Shared plumbing: delegate everything, intercept step()."""

    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, item):
        return getattr(self._inner, item)

    @property
    def inner_opt(self):
        return self._inner


class LocalSGDOptimizer(_WrappedOptimizer):
    """LocalSGD (reference meta_optimizers/localsgd_optimizer.py): run
    ``k_steps`` purely-local updates, then average parameters across the
    data-parallel group. Halves+ comm frequency at the cost of staleness.
    """

    def __init__(self, optimizer, k_steps: int = 4, group=None):
        super().__init__(optimizer)
        self.k_steps = max(int(k_steps), 1)
        self._group = group
        self._step_count = 0

    def step(self):
        self._inner.step()
        self._step_count += 1
        if self._step_count % self.k_steps == 0:
            self._average_params()

    def _average_params(self):
        from .. import collective
        from ...autograd import engine as ag
        pl = getattr(self._inner, "_parameter_list", None) or \
            getattr(self._inner, "_parameters", [])
        with ag.no_grad():  # a comm epilogue, not part of any autodiff graph
            for p in pl:
                collective.all_reduce(p, op=collective.ReduceOp.AVG,
                                      group=self._group)

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.clear_grad()


class DGCMomentumOptimizer(_WrappedOptimizer):
    """Deep Gradient Compression (reference dgc_optimizer.py + dgc_op.cc):
    before communication, keep only the top ``sparsity`` fraction of each
    gradient by magnitude; the residual accumulates locally with momentum
    correction and is added back next step (error feedback).

    TPU note: the "sparse" gradient stays DENSE-masked (scatter of a
    masked tensor) — ICI allreduce of a mostly-zero dense tensor is how
    XLA would lower a sparse collective anyway; the statistical behavior
    (only top-k% of updates communicated per step) matches the reference.
    """

    def __init__(self, optimizer, rampup_begin_step: int = 0,
                 sparsity: float = 0.01, momentum: float = 0.9):
        super().__init__(optimizer)
        self.sparsity = float(sparsity)
        self.momentum = float(momentum)
        self.rampup_begin_step = int(rampup_begin_step)
        self._u: Dict[int, Any] = {}   # momentum residual per param
        self._v: Dict[int, Any] = {}   # error feedback per param
        self._steps = 0

    def step(self):
        import jax.numpy as jnp
        self._steps += 1
        if self._steps > self.rampup_begin_step:
            pl = getattr(self._inner, "_parameter_list", None) or \
                getattr(self._inner, "_parameters", [])
            for p in pl:
                if p.grad is None:
                    continue
                g = p.grad.data
                u = self._u.get(id(p))
                u = g if u is None else self.momentum * u + g
                v = self._v.get(id(p))
                v = u if v is None else v + u
                flat = jnp.abs(v).reshape(-1)
                k = max(1, int(flat.shape[0] * self.sparsity))
                thresh = jnp.sort(flat)[-k]
                mask = (jnp.abs(v) >= thresh)
                send = jnp.where(mask, v, 0)
                self._v[id(p)] = jnp.where(mask, 0, v)   # residual stays
                self._u[id(p)] = jnp.where(mask, 0, u)
                p.grad = Tensor(send, stop_gradient=True)
        self._inner.step()

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.clear_grad()
