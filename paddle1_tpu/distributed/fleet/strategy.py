"""DistributedStrategy — the fleet configuration surface.

Analog of the reference's ``DistributedStrategy`` façade
(python/paddle/distributed/fleet/base/distributed_strategy.py over the proto
paddle/fluid/framework/distributed_strategy.proto:146-196). Every toggle the
reference exposes is kept; fields whose mechanism is subsumed by XLA (e.g.
fuse_all_reduce_ops — XLA fuses collectives; nccl_comm_num — ICI has no user
ring management) are accepted for compatibility and recorded, but are no-ops
by design, documented per-field below.
"""

from __future__ import annotations

import copy
from typing import Any, Dict

__all__ = ["DistributedStrategy"]


_DEFAULTS: Dict[str, Any] = {
    # --- mixed precision (reference proto field amp / amp_configs) ---
    "amp": False,
    "amp_configs": {
        "init_loss_scaling": 32768.0,
        "incr_every_n_steps": 1000,
        "decr_every_n_nan_or_inf": 2,
        "incr_ratio": 2.0,
        "decr_ratio": 0.8,
        "use_dynamic_loss_scaling": True,
        "custom_white_list": [],
        "custom_black_list": [],
        "use_pure_fp16": False,
        "use_fp16_guard": True,
        "use_bf16": True,  # TPU-native default: bf16 needs no loss scaling
    },
    # --- recompute ---
    "recompute": False,
    "recompute_configs": {"checkpoints": [], "enable_offload": False},
    # --- pipeline ---
    "pipeline": False,
    "pipeline_configs": {"accumulate_steps": 1, "micro_batch_size": 1,
                         "schedule_mode": "1F1B"},
    # --- tensor parallel (static-mode naming) ---
    "tensor_parallel": False,
    "tensor_parallel_configs": {"tensor_parallel_degree": 1,
                                "tensor_init_seed": -1},
    # --- ZeRO sharding ---
    "sharding": False,
    "sharding_configs": {"sharding_degree": 1, "stage": 2,
                         "segment_broadcast_MB": 32.0,
                         "offload": False, "hybrid_dp": False},
    # --- hybrid (dygraph naming) ---
    "hybrid_configs": {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                       "sharding_degree": 1, "sep_degree": 1},
    # --- gradient merge / accumulation ---
    "gradient_merge": False,
    "gradient_merge_configs": {"k_steps": 1, "avg": True},
    # --- device-resident multi-step training (TPU-native addition):
    #     fuse k optimizer steps into ONE jitted executable
    #     (ParallelEngine.step_many / step_stream) — k dispatches and k
    #     loss readbacks collapse to one of each ---
    "train_steps_per_sync": 1,
    # --- localsgd ---
    "localsgd": False,
    "localsgd_configs": {"k_steps": 1, "begin_step": 1},
    "adaptive_localsgd": False,
    "adaptive_localsgd_configs": {"init_k_steps": 1, "begin_step": 1},
    # --- large-batch optimizers ---
    "lamb": False,
    "lamb_configs": {"lamb_weight_decay": 0.01, "exclude_from_weight_decay": []},
    "lars": False,
    "lars_configs": {"lars_coeff": 0.001, "lars_weight_decay": 0.0005,
                     "epsilon": 0.0, "exclude_from_weight_decay": []},
    # --- gradient compression (accepted; DGC's CUDA kernels have no TPU
    #     analog — fp16/bf16 grad comm via amp covers the bandwidth goal) ---
    "dgc": False,
    "dgc_configs": {"rampup_begin_step": 0, "rampup_step": 1,
                    "sparsity": [0.999]},
    "fp16_allreduce": False,
    # --- collective transport knobs: XLA/ICI owns these; recorded only ---
    "nccl_comm_num": 1,
    "use_hierarchical_allreduce": False,
    "hierarchical_allreduce_inter_nranks": 1,
    "fuse_all_reduce_ops": True,
    "fuse_grad_size_in_MB": 32,
    "sync_nccl_allreduce": True,
    # --- batch norm ---
    "sync_batch_norm": False,
    # --- PS / async ---
    "a_sync": False,
    "a_sync_configs": {"k_steps": -1, "max_merge_var_num": 1,
                       "send_queue_size": 16, "independent_recv_thread": False,
                       "thread_pool_size": 1, "send_wait_times": 1,
                       "runtime_split_send_recv": False, "launch_barrier": True},
    # --- elastic (flag-only in the reference too, proto:157) ---
    "elastic": False,
    # --- execution ---
    "auto": False,
    "semi_auto": False,
    "without_graph_optimization": False,
}


class DistributedStrategy:
    """Attribute-style strategy bag with the reference's field set."""

    def __init__(self):
        self.__dict__["_conf"] = copy.deepcopy(_DEFAULTS)
        # flag-defaulted fields, resolved at construction (not import)
        # so set_flags before building a strategy takes effect
        from ...core import flags as core_flags
        self.__dict__["_conf"]["use_hierarchical_allreduce"] = bool(
            core_flags.flag("hierarchical_allreduce"))

    def __getattr__(self, name):
        conf = self.__dict__.get("_conf", {})
        if name in conf:
            return conf[name]
        raise AttributeError(f"DistributedStrategy has no field {name!r}")

    def __setattr__(self, name, value):
        conf = self.__dict__["_conf"]
        if name not in conf:
            raise AttributeError(f"DistributedStrategy has no field {name!r}")
        current = conf[name]
        if isinstance(current, dict) and isinstance(value, dict):
            merged = dict(current)
            merged.update(value)
            conf[name] = merged
        else:
            conf[name] = value

    def to_dict(self) -> Dict[str, Any]:
        return copy.deepcopy(self._conf)

    def __repr__(self):
        on = [k for k, v in self._conf.items()
              if isinstance(v, bool) and v]
        return f"DistributedStrategy(enabled={on})"
