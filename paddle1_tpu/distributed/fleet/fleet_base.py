"""Fleet façade — the user-facing distributed API.

Analog of the reference's ``Fleet`` singleton
(python/paddle/distributed/fleet/base/fleet_base.py:71 init, :712
distributed_optimizer, :765 distributed_model, :1212 minimize). The
reference's ``minimize`` chains *meta-optimizers* that each rewrite the
ProgramDesc (insert AMP casts, recompute segments, c_allreduce ops,
pipeline sections…). The TPU architecture replaces program rewriting with
**sharding-rule composition**: ``fleet.init`` builds one nd device mesh from
``hybrid_configs``; ``distributed_model``/``distributed_optimizer`` attach
the right axis semantics (dp grad-sync, mp layer axes, sharded optimizer
states); the XLA compiler then emits the collectives the reference's
rewritten programs carried explicitly.
"""

from __future__ import annotations

import os
from typing import Optional

from ...core.errors import PreconditionNotMetError
from ...nn.layer_base import Layer
from .. import env
from ..parallel import DataParallel, init_parallel_env
from ..topology import (CommunicateTopology, HybridCommunicateGroup,
                        set_hybrid_communicate_group,
                        get_hybrid_communicate_group)
from .role_maker import PaddleCloudRoleMaker, RoleMakerBase
from .strategy import DistributedStrategy

__all__ = ["Fleet", "fleet"]


class Fleet:
    """Singleton (reference fleet_base.py:71)."""

    def __init__(self):
        self._role_maker: Optional[RoleMakerBase] = None
        self._user_defined_strategy: Optional[DistributedStrategy] = None
        self._hcg: Optional[HybridCommunicateGroup] = None
        self._is_initialized = False

    # -- init ---------------------------------------------------------------

    def init(self, role_maker: Optional[RoleMakerBase] = None,
             is_collective: bool = True,
             strategy: Optional[DistributedStrategy] = None) -> "Fleet":
        self._role_maker = role_maker or PaddleCloudRoleMaker(
            is_collective=is_collective)
        self._user_defined_strategy = strategy or DistributedStrategy()
        init_parallel_env()

        hc = self._user_defined_strategy.hybrid_configs
        import jax
        n_dev = len(jax.devices())
        degrees = {"dp": hc.get("dp_degree", 1), "mp": hc.get("mp_degree", 1),
                   "pp": hc.get("pp_degree", 1),
                   "sharding": hc.get("sharding_degree", 1),
                   "sp": hc.get("sep_degree", 1)}
        total = 1
        for v in degrees.values():
            total *= max(1, v)
        if total == 1 and n_dev > 1:
            degrees["dp"] = n_dev  # default: pure DP over every chip
            total = n_dev
        if total <= n_dev:
            topo = CommunicateTopology(
                ["pp", "dp", "sharding", "mp", "sp"],
                [degrees["pp"], degrees["dp"], degrees["sharding"],
                 degrees["mp"], degrees["sp"]])
            from ..topology import build_mesh
            mesh = build_mesh(dp=degrees["dp"], mp=degrees["mp"],
                              pp=degrees["pp"],
                              sharding=degrees["sharding"],
                              sp=degrees["sp"],
                              devices=jax.devices()[:total])
            self._hcg = HybridCommunicateGroup(topo, mesh=mesh)
            set_hybrid_communicate_group(self._hcg)
        else:
            raise PreconditionNotMetError(
                f"hybrid_configs {degrees} need {total} devices, "
                f"have {n_dev}")
        self._is_initialized = True
        return self

    # -- role queries (reference fleet_base.py:340-510) ---------------------

    def _ensure_init(self):
        if not self._is_initialized:
            raise PreconditionNotMetError(
                "fleet.init() must be called first")

    def is_first_worker(self) -> bool:
        self._ensure_init()
        return self._role_maker.is_first_worker()

    def worker_index(self) -> int:
        self._ensure_init()
        return self._role_maker.worker_index()

    def worker_num(self) -> int:
        self._ensure_init()
        return self._role_maker.worker_num()

    def is_worker(self) -> bool:
        self._ensure_init()
        return self._role_maker.is_worker()

    def is_server(self) -> bool:
        self._ensure_init()
        return self._role_maker.is_server()

    def worker_endpoints(self, to_string: bool = False):
        self._ensure_init()
        eps = self._role_maker.get_trainer_endpoints()
        return ",".join(eps) if to_string else eps

    def barrier_worker(self):
        self._ensure_init()
        self._role_maker._barrier()

    # PS-mode entry points: the brpc parameter server has no ICI analog
    # (SURVEY §7 hard part f); its scoped replacement for the sparse
    # workload is distributed.ps.EmbeddingService + fleet.MultiTrainer.
    # init_worker/init_server stay callable (scripts call them before the
    # strategy decides the mode) but a PS-only training entry raises
    # loudly instead of silently no-op'ing.
    def init_worker(self):
        self._ensure_init()

    def init_server(self, *args, dim: int = None, table_kwargs: dict = None,
                    dense_tables: dict = None, **kwargs):
        """Create this PS node's table shard (reference fleet.init_server
        loads the server program; here the 'program' is one SparseTable
        plus optional named dense blocks — the reference PS node's
        sparse + CommonDenseTable pairing).
        ``dim`` may come as a kwarg or via ``PADDLE_PS_TABLE_DIM``;
        ``dense_tables`` maps name → shape tuple (or a prebuilt
        :class:`~paddle1_tpu.distributed.ps.DenseTable`)."""
        self._ensure_init()
        import os
        if dim is None:
            dim = int(os.environ.get("PADDLE_PS_TABLE_DIM", "0"))
        if dim <= 0:
            raise PreconditionNotMetError(
                "init_server needs the table dim: fleet.init_server(dim=D) "
                "or env PADDLE_PS_TABLE_DIM")
        from ..ps import DenseTable, SparseTable
        self._server_table = SparseTable(dim, **(table_kwargs or {}))
        self._server_dense = {
            name: (spec if isinstance(spec, DenseTable)
                   else DenseTable(spec, **(table_kwargs or {})))
            for name, spec in (dense_tables or {}).items()}

    def run_server(self):
        """Serve this node's table shard over TCP, blocking (reference
        fleet.run_server → brpc_ps_server). Needs init_server first and a
        port from ``PADDLE_PORT``. Trainers reach the table fleet via
        distributed.ps_server.remote_service(dim,
        PADDLE_PSERVERS_IP_PORT_LIST.split(','))."""
        self._ensure_init()
        import os
        table = getattr(self, "_server_table", None)
        if table is None:
            raise PreconditionNotMetError(
                "run_server: call fleet.init_server(dim=...) first")
        from ..ps_server import TableServer
        port_s = os.environ.get("PADDLE_PORT")
        if port_s is None:
            raise PreconditionNotMetError(
                "run_server: PADDLE_PORT is not set — trainers dial the "
                "CONFIGURED endpoint from PADDLE_PSERVERS_IP_PORT_LIST, so "
                "an OS-assigned ephemeral port can never be reached. Set "
                "PADDLE_PORT to this server's port (0 only for tests that "
                "read the bound port back from fleet._table_server)")
        port = int(port_s)
        host = os.environ.get("POD_IP", "127.0.0.1")
        srv = TableServer(table, host=host, port=port,
                          aux_tables=getattr(self, "_server_dense", None))
        self._table_server = srv
        srv.serve_forever()

    def stop_worker(self):
        pass

    def save_inference_model(self, executor=None, dirname: str = None,
                             feeded_var_names=None, target_vars=None,
                             main_program=None, export_for_deployment=True,
                             *, model=None, input_spec=None):
        """PS/collective checkpoint of the serving program (reference
        fleet save_inference_model → jit.save artifact here). Rank 0
        writes; other workers no-op (the reference gates on
        is_first_worker the same way)."""
        self._ensure_init()
        if not self.is_first_worker():
            return
        if model is None or input_spec is None or dirname is None:
            raise PreconditionNotMetError(
                "fleet.save_inference_model(dirname=..., model=..., "
                "input_spec=[InputSpec(...)]) — the StableHLO artifact "
                "needs the Layer and its input shapes (the reference "
                "read them from the feed/fetch vars of a Program)")
        import os as _os
        from ... import jit as _jit
        _jit.save(model, _os.path.join(dirname, "model"),
                  input_spec=input_spec)

    def _ps_shard_id(self) -> int:
        """This node's shard identity for table checkpoints. Servers are
        launched with PADDLE_SERVER_ID (launch_utils PS mode), NOT a
        trainer rank — worker_index() is 0 on every server, so keying
        shards on it would make all servers collide on one file."""
        import os as _os
        sid = _os.environ.get("PADDLE_SERVER_ID")
        return int(sid) if sid is not None else self.worker_index()

    def save_persistables(self, executor=None, dirname: str = None,
                          main_program=None, mode: int = 0, *,
                          model=None):
        """Persist trainable state + this node's PS tables (reference
        fleet save_persistables: dense vars + the server's table
        shards). Dense params write from worker rank 0; each SERVER
        writes its own ps_shard_<server_id> file."""
        self._ensure_init()
        if dirname is None:
            raise PreconditionNotMetError(
                "fleet.save_persistables needs dirname=")
        import os as _os
        _os.makedirs(dirname, exist_ok=True)
        from ...framework.io import save as _fsave
        table = getattr(self, "_server_table", None)
        if (model is not None and self.is_first_worker()
                and table is None):
            _fsave(model.state_dict(),
                   _os.path.join(dirname, "dense.pdparams"))
        if table is not None:
            states = {"sparse": table.state_dict(),
                      "dense_tables": {
                          n: t.state_dict()
                          for n, t in getattr(self, "_server_dense",
                                              {}).items()}}
            _fsave(states, _os.path.join(
                dirname, f"ps_shard_{self._ps_shard_id()}.pkl"))

    def load_persistables(self, executor=None, dirname: str = None,
                          main_program=None, mode: int = 0, *,
                          model=None):
        """Restore what save_persistables wrote (this node's view)."""
        self._ensure_init()
        if dirname is None:
            raise PreconditionNotMetError(
                "fleet.load_persistables needs dirname=")
        import os as _os
        from ...framework.io import load as _fload
        dense = _os.path.join(dirname, "dense.pdparams")
        if model is not None and _os.path.exists(dense):
            model.set_state_dict(_fload(dense))
        shard = _os.path.join(dirname,
                              f"ps_shard_{self._ps_shard_id()}.pkl")
        table = getattr(self, "_server_table", None)
        if table is not None and _os.path.exists(shard):
            states = _fload(shard, return_numpy=True)
            table.load_state_dict(states["sparse"])
            for n, sd in states.get("dense_tables", {}).items():
                if n in getattr(self, "_server_dense", {}):
                    self._server_dense[n].load_state_dict(sd)

    # -- the distributed wrappers ------------------------------------------

    @property
    def _strategy(self) -> DistributedStrategy:
        return self._user_defined_strategy or DistributedStrategy()

    def get_hybrid_communicate_group(self) -> HybridCommunicateGroup:
        self._ensure_init()
        return self._hcg

    def distributed_model(self, model: Layer):
        """Wrap for the active parallelism mix (reference fleet_base.py:765:
        dygraph → DataParallel; hybrid → meta_parallel wrappers)."""
        self._ensure_init()
        hcg = self._hcg
        if hcg.get_pipe_parallel_world_size() > 1:
            from ..meta_parallel.pipeline_parallel import PipelineParallel
            return PipelineParallel(model, hcg, self._strategy)
        if hcg.get_model_parallel_world_size() > 1:
            from ..meta_parallel.model_parallel import ModelParallel
            return ModelParallel(model, hcg, self._strategy)
        fp16_comm = bool(self._strategy and self._strategy.fp16_allreduce)
        return DataParallel(model,
                            group=hcg.get_data_parallel_group(),
                            comm_dtype="bfloat16" if fp16_comm else None)

    def distributed_optimizer(self, optimizer,
                              strategy: Optional[DistributedStrategy] = None):
        """Reference fleet_base.py:712. Returns a HybridParallelOptimizer
        bound to the mesh (grad sync over the right axes, optional ZeRO
        sharding of optimizer states)."""
        self._ensure_init()
        if strategy is not None:
            self._user_defined_strategy = strategy
        from .meta_optimizers import apply_optimizer_meta
        optimizer = apply_optimizer_meta(optimizer, self._strategy)
        from ..meta_parallel.hybrid_optimizer import HybridParallelOptimizer
        return HybridParallelOptimizer(optimizer, self._hcg, self._strategy)

    def distributed_scaler(self, scaler):
        """Wrap GradScaler so found_inf is any-reduced across the mp group
        and all ranks skip the same steps (reference
        dygraph_optimizer/hybrid_parallel_gradscaler.py)."""
        self._ensure_init()
        from ..meta_parallel.hybrid_optimizer import \
            HybridParallelGradScaler
        return HybridParallelGradScaler(scaler, self._hcg)

    def parallel_engine(self, model: Layer, optimizer, loss_fn,
                        mesh=None, **overrides):
        """Compile the active DistributedStrategy into a ParallelEngine —
        the TPU-native fleet.minimize (reference fleet_base.py:1212 →
        StrategyCompiler → chained meta-optimizer rewrites; here: one
        strategy→engine-config mapping, one jit)."""
        self._ensure_init()
        from .meta_optimizers import compile_strategy
        cfg = compile_strategy(self._strategy)
        cfg.update(overrides)
        if mesh is not None:
            cfg.pop("degrees", None)
        from ..parallel_engine import ParallelEngine
        return ParallelEngine(model, optimizer, loss_fn, mesh=mesh, **cfg)

    def minimize(self, optimizer, loss=None, startup_program=None,
                 parameter_list=None, no_grad_set=None):
        """Static-mode minimize (reference fleet_base.py:1212). In the TPU
        build strategy composition happens in the optimizer/model wrappers;
        minimize just delegates."""
        self._ensure_init()
        if loss is not None and hasattr(optimizer, "minimize"):
            return optimizer.minimize(loss)
        return None

    # misc
    @property
    def util(self):
        from .utils import fleet_util
        return fleet_util


fleet = Fleet()
