"""Trainer / DeviceWorker runtime: MultiTrainer + HogwildWorker.

Analog of the reference's C++ trainer family
(/root/reference/paddle/fluid/framework/trainer.h:57 MultiTrainer,
hogwild_worker.cc HogwildWorker, executor.train_from_dataset — the
industrial CPU training loop: N worker threads drain a Dataset channel,
each runs forward/backward and applies updates asynchronously).

TPU-native scoping: the *dense* model path on TPU is the compiled
ParallelEngine — this runtime exists for the reference's other half, the
host-side sparse/CPU workload: embedding-heavy models over
:class:`~paddle1_tpu.distributed.ps.EmbeddingService` tables (whose
per-shard locks make concurrent push/pull safe) fed by the out-of-core
file datasets. Worker threads compute forward/backward concurrently
(jax host ops release the GIL); the dense update application is
serialized on a short lock — the asynchronous, slightly-stale update
semantics of Hogwild, with the slot-state races removed. Sparse pushes
through DistributedEmbedding hooks stay fully concurrent.

For GIL-bound workloads (slot parsing, python feature engineering) use
:class:`~paddle1_tpu.distributed.fleet.process_trainer.
ProcessMultiTrainer` — real process workers over the shm arena with the
same Hogwild semantics and actual multi-core throughput.
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable, List, Optional

import numpy as np

from ...core.errors import InvalidArgumentError

__all__ = ["HogwildWorker", "InferWorker", "MultiTrainer", "TrainerDesc",
           "DeviceWorkerDesc", "create_trainer"]


def _batched(sample_iter: Iterable, batch_size: int, collate: Callable):
    buf = []
    for s in sample_iter:
        buf.append(s)
        if len(buf) == batch_size:
            yield collate(buf)
            buf = []
    if buf:
        yield collate(buf)


class HogwildWorker(threading.Thread):
    """One device-worker thread (reference hogwild_worker.cc: TrainFiles
    pulls from the data channel until empty, fwd/bwd/update per batch)."""

    def __init__(self, worker_id: int, batch_iter, iter_lock, step_lock,
                 loss_fn: Callable, optimizer, stats: dict):
        super().__init__(daemon=True, name=f"hogwild-{worker_id}")
        self.worker_id = worker_id
        self._batch_iter = batch_iter
        self._iter_lock = iter_lock
        self._step_lock = step_lock
        self._loss_fn = loss_fn
        self._optimizer = optimizer
        self._stats = stats
        self.error: Optional[BaseException] = None

    def run(self):
        losses, n = [], 0
        try:
            while True:
                with self._iter_lock:
                    batch = next(self._batch_iter, None)
                if batch is None:
                    break
                loss = self._loss_fn(batch)
                loss.backward()
                with self._step_lock:
                    self._optimizer.step()
                    self._optimizer.clear_grad()
                losses.append(float(loss.numpy()))
                n += 1
        except BaseException as e:  # noqa: broad-except — stored and
            # re-raised by the coordinating thread after join
            self.error = e
        self._stats[self.worker_id] = {"batches": n, "losses": losses}


class MultiTrainer:
    """Reference framework/trainer.h MultiTrainer + the
    executor.train_from_dataset entry (fluid/executor.py:1113)."""

    def __init__(self, thread_num: int = 1):
        if thread_num < 1:
            raise InvalidArgumentError("thread_num must be >= 1")
        self.thread_num = int(thread_num)

    def _drain(self, dataset, batch_size, collate, make_worker) -> dict:
        """Shared worker drain: batch the dataset once, spawn
        ``thread_num`` workers via ``make_worker(i, batch_iter,
        iter_lock, stats)``, join, re-raise the first worker error."""
        if collate is None:
            collate = lambda buf: np.stack(buf)
        if batch_size is None:
            batch_iter = iter(dataset)
        else:
            batch_iter = _batched(iter(dataset), batch_size, collate)
        iter_lock = threading.Lock()
        stats: dict = {}
        workers = [make_worker(i, batch_iter, iter_lock, stats)
                   for i in range(self.thread_num)]
        for w in workers:
            w.start()
        for w in workers:
            w.join()
        for w in workers:
            if w.error is not None:
                raise w.error
        return stats

    def train_from_dataset(self, dataset, loss_fn: Callable, optimizer,
                           batch_size: int = 1,
                           collate: Optional[Callable] = None,
                           debug: bool = False) -> dict:
        """Drain ``dataset`` once across ``thread_num`` workers.

        ``dataset``: any iterable of samples (QueueDataset streams
        out-of-core; InMemoryDataset after load_into_memory) — or an
        iterable of ready batches with ``batch_size=None``.
        ``loss_fn(batch) -> scalar Tensor`` runs the eager model.
        Returns aggregate stats (reference prints fetch vars per period;
        the per-worker loss series is returned instead).
        """
        step_lock = threading.Lock()
        stats = self._drain(
            dataset, batch_size, collate,
            lambda i, it, it_lock, st: HogwildWorker(
                i, it, it_lock, step_lock, loss_fn, optimizer, st))
        all_losses: List[float] = []
        for s in stats.values():
            all_losses.extend(s["losses"])
        out = {"workers": self.thread_num,
               "batches": sum(s["batches"] for s in stats.values()),
               "loss_mean": float(np.mean(all_losses)) if all_losses
               else float("nan"),
               "per_worker": stats}
        if debug:
            print(f"MultiTrainer: {out['batches']} batches over "
                  f"{self.thread_num} workers, mean loss "
                  f"{out['loss_mean']:.6f}")
        return out

    def infer_from_dataset(self, dataset, infer_fn: Callable,
                           batch_size: int = 1,
                           collate: Optional[Callable] = None,
                           fetch_handler: Optional[Callable] = None,
                           debug: bool = False) -> dict:
        """Drain ``dataset`` once through ``infer_fn(batch) -> out``
        with no optimizer (reference executor.infer_from_dataset,
        fluid/executor.py:1539: same trainer runtime, infer_mode on).

        With ``fetch_handler`` each batch's output is handed to it and
        not retained (the reference's FetchHandler role); otherwise all
        outputs are collected under ``per_worker``.
        """
        handler_lock = threading.Lock()
        stats = self._drain(
            dataset, batch_size, collate,
            lambda i, it, it_lock, st: InferWorker(
                i, it, it_lock, infer_fn, fetch_handler, handler_lock,
                st))
        out = {"workers": self.thread_num,
               "batches": sum(s["batches"] for s in stats.values()),
               "per_worker": stats}
        if debug:
            print(f"MultiTrainer(infer): {out['batches']} batches over "
                  f"{self.thread_num} workers")
        return out


class InferWorker(threading.Thread):
    """Inference twin of HogwildWorker (reference device_worker.h
    HogwildWorker with infer_mode / executor.infer_from_dataset,
    fluid/executor.py:1539): drains batches, runs forward only, no
    optimizer step."""

    def __init__(self, worker_id: int, batch_iter, iter_lock,
                 infer_fn: Callable, fetch_handler, handler_lock,
                 stats: dict):
        super().__init__(daemon=True, name=f"infer-{worker_id}")
        self.worker_id = worker_id
        self._batch_iter = batch_iter
        self._iter_lock = iter_lock
        self._infer_fn = infer_fn
        self._fetch_handler = fetch_handler
        self._handler_lock = handler_lock
        self._stats = stats
        self.error: Optional[BaseException] = None

    def run(self):
        outputs, n = [], 0
        try:
            while True:
                with self._iter_lock:
                    batch = next(self._batch_iter, None)
                if batch is None:
                    break
                out = self._infer_fn(batch)
                if self._fetch_handler is not None:
                    # serialized like the reference's single
                    # FetchHandlerMonitor thread — handlers may do
                    # read-modify-write or file IO
                    with self._handler_lock:
                        self._fetch_handler(out)
                else:
                    outputs.append(out)
                n += 1
        except BaseException as e:  # noqa: broad-except — stored and
            # re-raised by the coordinating thread after join
            self.error = e
        self._stats[self.worker_id] = {"batches": n, "outputs": outputs}


class DeviceWorkerDesc:
    """Which worker runs each slot of the trainer (reference
    trainer_desc.proto DeviceWorkerDesc / device_worker_factory.cc).
    ``hogwild`` → shared-memory async workers; ``section`` (pipeline)
    maps to meta_parallel.PipelineParallel and is routed there."""

    KINDS = ("hogwild", "section")

    def __init__(self, kind: str = "hogwild"):
        if kind not in self.KINDS:
            raise InvalidArgumentError(
                f"device worker {kind!r}; available: {self.KINDS} "
                "(DownpourSV/PSGPU map onto hogwild + the PS tables; "
                "heter workers have no TPU meaning)")
        self.kind = kind


class TrainerDesc:
    """Trainer configuration (reference framework/trainer_desc.proto +
    trainer_factory.cc): picks the trainer family and its concurrency.
    ``thread_num`` → GIL-sharing thread workers (MultiTrainer);
    ``process_num`` → real process workers over the shm arena
    (ProcessMultiTrainer — the HogwildWorker-throughput form)."""

    def __init__(self, thread_num: int = 1, process_num: int = 0,
                 device_worker: "DeviceWorkerDesc" = None,
                 publish_interval: int = 4):
        self.thread_num = int(thread_num)
        self.process_num = int(process_num)
        self.device_worker = device_worker or DeviceWorkerDesc()
        self.publish_interval = int(publish_interval)


def create_trainer(desc: TrainerDesc):
    """trainer_factory.cc analog: desc → trainer instance."""
    if desc.device_worker.kind == "section":
        raise InvalidArgumentError(
            "section (pipeline) workers: build the model with "
            "meta_parallel.PipelineLayer and train with "
            "PipelineParallel.train_batch (the 1F1B schedule), or use "
            "ParallelEngine(pp=...) for the in-graph form")
    if desc.process_num and desc.process_num > 0:
        from .process_trainer import ProcessMultiTrainer
        return ProcessMultiTrainer(process_num=desc.process_num,
                                   publish_interval=desc.publish_interval)
    return MultiTrainer(thread_num=max(desc.thread_num, 1))
