"""Process-based Hogwild workers over the shared-memory arena.

The reference's HogwildWorker is a lock-free C++ thread
(/root/reference/paddle/fluid/framework/device_worker.h:150,
hogwild_worker.cc) — real parallel CPU throughput. The r3
:class:`~paddle1_tpu.distributed.fleet.trainer.MultiTrainer` runs
Python threads, which demonstrate the composition shape but serialize
on the GIL for the slot-parsing/feature work that dominates the CPU-PS
workload. This module is the throughput-bearing version:

* N worker **processes**, each with its own interpreter (no GIL
  sharing), built from a picklable ``model_fn``.
* Batches and gradients cross process boundaries as shared-memory
  descriptors over the :class:`~paddle1_tpu.core.native.ShmArena`
  (native.cc block allocator + refcounts) — numpy payloads are written
  once and read zero-copy; only tiny descriptor tuples travel through
  the queues.
* The **dense update stays serialized in the parent** (the reference
  Hogwild races updates benignly; here the parent applies each worker
  gradient to the master model through the real optimizer — the same
  slightly-stale async semantics without slot-state races), and fresh
  parameters broadcast back through the arena every
  ``publish_interval`` updates.
* The arena is a bump allocator (blocks reclaim on ``reset`` only), so
  the parent runs a drain-and-reset barrier when usage crosses a
  threshold: stop issuing tasks, absorb in-flight grads, reset,
  republish params.
* Sparse parameters compose unchanged: a ``DistributedEmbedding``
  inside ``model_fn``'s model pushes/pulls against the PS tables
  (process-safe TCP transport), exactly the Downpour split.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import queue as pyqueue
import time
from typing import Callable, Iterable, Optional

import numpy as np

from ...core.errors import InvalidArgumentError

__all__ = ["ProcessMultiTrainer"]


def _orphan_checked_get(q, timeout, what):
    """``q.get`` that notices a dead leader. Workers block on
    ``param_q``/``task_q`` gets; if the parent died (SIGKILL skips the
    daemon-reaping atexit hook, orphaning spawn children), the plain
    get would hang 120s — or forever in the inner loops. Poll in short
    slices and check parent liveness between them; raises RuntimeError
    with the real cause instead. ``timeout=None`` blocks indefinitely
    (while the parent lives); a finite timeout re-raises ``Empty`` at
    its deadline, preserving the plain-get contract."""
    deadline = None if timeout is None else time.monotonic() + timeout
    while True:
        slice_s = 2.0
        if deadline is not None:
            slice_s = min(slice_s, max(0.05, deadline - time.monotonic()))
        try:
            return q.get(timeout=slice_s)
        except pyqueue.Empty:
            parent = mp.parent_process()
            if parent is not None and not parent.is_alive():
                raise RuntimeError(
                    f"hogwild worker orphaned: the leader process died "
                    f"while this worker waited for {what} — exiting "
                    "instead of hanging on the queue")
            if deadline is not None and time.monotonic() >= deadline:
                raise


# -- shm pytree transport ----------------------------------------------------

def _tree_put(arena, obj):
    """numpy-pytree → descriptor-pytree. ndarray payloads go through the
    arena; strings and plain scalars (slot lines, labels, meta) ride the
    descriptor itself."""
    if isinstance(obj, dict):
        return {"__d": {k: _tree_put(arena, v) for k, v in obj.items()}}
    if isinstance(obj, (list, tuple)):
        return {"__l": [_tree_put(arena, v) for v in obj]}
    if isinstance(obj, (str, bytes, int, float, bool, type(None))):
        return {"__v": obj}
    return {"__a": arena.put_array(np.asarray(obj))}


def _tree_get(arena, desc, decref=True):
    if "__d" in desc:
        return {k: _tree_get(arena, v, decref)
                for k, v in desc["__d"].items()}
    if "__l" in desc:
        return [_tree_get(arena, v, decref) for v in desc["__l"]]
    if "__v" in desc:
        return desc["__v"]
    arr = arena.get_array(desc["__a"])
    if decref:
        arena.decref(desc["__a"])
    return arr


def _worker_main(worker_id, arena_name, task_q, grad_q, param_q,
                 epoch, model_fn, loss_fn, env):
    """Worker process entry (module-level: spawn-picklable)."""
    os.environ.update(env)
    os.environ["P1T_HOGWILD_WORKER"] = "1"  # lets factories detect workers
    # the CPU-PS workload never touches the TPU; never let a worker
    # try to claim the chip (or hang on a wedged tunnel)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", "cpu")

    from ...core import health, native
    from ...core.tensor import Tensor

    arena = native.ShmArena(arena_name, create=False)
    model = model_fn()
    # structured state_dict keys are replica-stable; Parameter.name uses a
    # process-global counter and need not agree between parent and worker
    tparams = {k: t for k, t in model.state_dict().items()
               if not t.stop_gradient}
    n_batches, losses = 0, []
    def adopt(msg):
        """Epoch-validated adoption: a message published before an arena
        reset points into reclaimed memory — discard it (the current
        params stay valid; the post-reset republish follows). The epoch
        is re-checked AFTER the copy-out to catch a reset racing the
        read."""
        ep, _ver, pdescs = msg
        if ep != epoch.value:
            return False
        # decref=False: a reset racing this read must see NO writes at
        # stale offsets (decref's fetch_sub would land inside freshly
        # allocated blocks); reset is the arena's only reclaimer anyway
        flat = _tree_get(arena, pdescs, decref=False)
        if ep != epoch.value:
            return False
        for name, p in tparams.items():
            p._data = Tensor(flat[name]).data
        return True

    version = 0
    try:
        # adopt the master's INITIAL params before any batch: per-process
        # model inits need not agree, and queue ordering across different
        # queues is not guaranteed
        while not adopt(_orphan_checked_get(param_q, 120,
                                            "the initial params")):
            pass
        while True:
            # supervisor liveness (no-op unless this worker tree runs
            # under a heartbeat channel) + worker-level chaos trigger
            health.beat()
            task = _orphan_checked_get(task_q, None, "the next task")
            if task is None:
                break
            # adopt the newest published params (drain to latest)
            newest = None
            while True:
                try:
                    newest = param_q.get_nowait()
                except pyqueue.Empty:
                    break
            if newest is not None:
                version = newest[1]
                adopt(newest)
            batch = _tree_get(arena, task)
            loss = loss_fn(model, batch)
            loss.backward()
            gdescs = {}
            for name, p in tparams.items():
                if p.grad is not None:
                    gdescs[name] = _tree_put(
                        arena, np.asarray(p.grad.numpy()))
                    p.clear_grad()
            losses.append(float(loss.numpy()))
            n_batches += 1
            grad_q.put(("grads", worker_id, gdescs, losses[-1], version))
    except BaseException as e:  # noqa: broad-except — surfaced to the
        # parent via the grad queue's error record; don't hang the join
        grad_q.put(("error", worker_id, repr(e), None, None))
        return
    finally:
        arena.close()
    grad_q.put(("exit", worker_id,
                {"batches": n_batches, "losses": losses}, None, None))


def _default_collate(buf):
    """Stack a list of samples: dict samples stack per key, tuple
    samples per position, array samples directly."""
    first = buf[0]
    if isinstance(first, dict):
        return {k: _default_collate([b[k] for b in buf]) for k in first}
    if isinstance(first, (list, tuple)):
        return type(first)(_default_collate([b[i] for b in buf])
                           for i in range(len(first)))
    if isinstance(first, str):
        return list(buf)
    return np.stack(buf)


def _tree_incref(arena, desc):
    if "__d" in desc:
        for v in desc["__d"].values():
            _tree_incref(arena, v)
    elif "__l" in desc:
        for v in desc["__l"]:
            _tree_incref(arena, v)
    elif "__a" in desc:
        arena.incref(desc["__a"])


def _batched(sample_iter: Iterable, batch_size, collate):
    buf = []
    for s in sample_iter:
        buf.append(s)
        if len(buf) == batch_size:
            yield collate(buf)
            buf = []
    if buf:
        yield collate(buf)


class ProcessMultiTrainer:
    """MultiTrainer with real process workers (reference HogwildWorker
    throughput semantics). ``model_fn``/``loss_fn`` must be picklable
    (module-level functions): each worker builds its own model replica;
    the parent holds the master copy and the optimizer."""

    def __init__(self, process_num: int = 2, arena_size: int = 1 << 27,
                 publish_interval: int = 4,
                 arena_reset_fraction: float = 0.6):
        if process_num < 1:
            raise InvalidArgumentError("process_num must be >= 1")
        self.process_num = int(process_num)
        self.arena_size = int(arena_size)
        self.publish_interval = int(publish_interval)
        self.arena_reset_fraction = float(arena_reset_fraction)

    def train_from_dataset(self, dataset, model_fn: Callable,
                           loss_fn: Callable, optimizer_fn: Callable,
                           batch_size: Optional[int] = 1,
                           collate: Optional[Callable] = None,
                           debug: bool = False) -> dict:
        """Drain ``dataset`` once across ``process_num`` worker
        processes. ``optimizer_fn(model) -> optimizer`` builds the
        parent-side optimizer over the master model."""
        from ...core import health, native
        from ...core.tensor import Tensor

        # the LEADER is the supervised process: adopt the heartbeat
        # channel now (beat() pops the PADDLE_FT_* env vars) so the
        # env snapshot below cannot leak it into the mp workers —
        # grandchildren beating the leader's file would mask a leader
        # hang from the supervisor
        health.beat()
        if not native.available():
            raise InvalidArgumentError(
                "ProcessMultiTrainer needs the native shm arena "
                "(core/native build); use MultiTrainer (threads) instead")
        if collate is None:
            collate = _default_collate
        batch_iter = iter(dataset) if batch_size is None else _batched(
            iter(dataset), batch_size, collate)

        master = model_fn()
        optimizer = optimizer_fn(master)
        tparams = {k: t for k, t in master.state_dict().items()
                   if not t.stop_gradient}

        arena_name = f"/p1t_hogwild_{os.getpid()}"
        lib = native._load()
        lib.shm_arena_unlink(arena_name.encode())
        arena = native.ShmArena(arena_name, self.arena_size)

        ctx = mp.get_context("spawn")
        task_q = ctx.Queue()
        grad_q = ctx.Queue()
        param_qs = [ctx.Queue() for _ in range(self.process_num)]
        epoch = ctx.Value("q", 0)  # arena-reset generation counter
        env = {k: v for k, v in os.environ.items()
               if k.startswith(("PADDLE_", "PYTHONPATH", "XLA_FLAGS"))}
        env["JAX_PLATFORMS"] = "cpu"
        procs = [ctx.Process(target=_worker_main,
                             args=(i, arena_name, task_q, grad_q,
                                   param_qs[i], epoch, model_fn, loss_fn,
                                   env),
                             daemon=True)
                 for i in range(self.process_num)]
        for p in procs:
            p.start()
        # exit-watching via the launcher's Supervisor (fail-fast,
        # detection only — check_failed() never takes policy action):
        # the mp workers are adopted through the Popen-shaped adapter
        from ..supervisor import MpProcessHandle, Supervisor
        watchdog = Supervisor(policy="fail_fast")
        for i, p in enumerate(procs):
            watchdog.attach(i, MpProcessHandle(p))

        def publish(version):
            # write the params into the arena ONCE; extra workers share
            # the blocks via incref (refcounted in native.cc)
            flat = {name: np.asarray(p.numpy())
                    for name, p in tparams.items()}
            descs = _tree_put(arena, flat)
            for q in param_qs[1:]:
                _tree_incref(arena, descs)
            ep = epoch.value
            for q in param_qs:
                q.put((ep, version, descs))

        stats: dict = {}
        outstanding = 0
        updates = 0
        version = 0
        exited = 0
        error = None
        draining = False

        def absorb(block):
            """Apply one grad message (or worker exit) from grad_q."""
            nonlocal outstanding, updates, version, exited, error
            deadline = 300
            while True:
                try:
                    kind, wid, payload, lossval, _v = grad_q.get(
                        timeout=5 if block else 0.001)
                    break
                except pyqueue.Empty:
                    if not block:
                        return False
                    # the leader is healthy while it waits here (its own
                    # 300s deadline tolerates slow workers) — keep the
                    # supervisor's hang detector fed
                    health.beat()
                    # a worker that died WITHOUT posting (unpicklable
                    # model_fn, missing __main__ guard in the caller's
                    # script, OOM-kill) would otherwise hang us forever
                    dead = watchdog.check_failed()
                    if len([p for p in procs if p.is_alive()]) + exited \
                            < self.process_num or dead:
                        raise RuntimeError(
                            "ProcessMultiTrainer: worker process died "
                            f"without reporting (exitcodes "
                            f"{[p.exitcode for p in procs]}). If your "
                            "script is the __main__ module, guard the "
                            "training call with if __name__ == "
                            "'__main__': (multiprocessing spawn "
                            "re-imports __main__)")
                    deadline -= 5
                    if deadline <= 0:
                        raise RuntimeError(
                            "ProcessMultiTrainer: no worker progress "
                            "in 300s")
            if kind == "error":
                error = RuntimeError(
                    f"hogwild worker {wid} failed: {payload}")
                exited += 1
                return True
            if kind == "exit":
                stats[wid] = payload
                exited += 1
                return True
            outstanding -= 1
            for name, gdesc in payload.items():
                g = _tree_get(arena, gdesc)
                tparams[name]._grad = Tensor(g)
            optimizer.step()
            optimizer.clear_grad()
            updates += 1
            if updates % self.publish_interval == 0 and not draining:
                # during the reset barrier the arena is near-full and a
                # fresh republish follows the reset anyway
                version += 1
                publish(version)
            return True

        try:
            publish(version)  # initial params
            while True:
                health.beat()  # leader liveness, once per dispatch round
                # memory barrier: drain in-flight, reset, republish
                if arena.used() > self.arena_size * self.arena_reset_fraction:
                    draining = True
                    while outstanding > 0 and error is None:
                        absorb(block=True)
                    draining = False
                    # bump the epoch FIRST: any pre-reset param message
                    # still in transit (mp.Queue feeder threads) is now
                    # stale and the workers discard it by epoch check
                    with epoch.get_lock():
                        epoch.value += 1
                    arena.reset()
                    version += 1
                    publish(version)
                if error is not None:
                    break
                batch = next(batch_iter, None)
                if batch is None:
                    break
                task_q.put(_tree_put(arena, batch))
                outstanding += 1
                while absorb(block=False):
                    pass
            for _ in procs:
                task_q.put(None)
            while exited < self.process_num:
                absorb(block=True)
        finally:
            for p in procs:
                p.join(timeout=30)
                if p.is_alive():
                    p.terminate()
            arena.close(unlink=True)
        if error is not None:
            raise error

        all_losses = [l for s in stats.values() for l in s["losses"]]
        out = {"workers": self.process_num,
               "batches": sum(s["batches"] for s in stats.values()),
               "updates": updates,
               "loss_mean": float(np.mean(all_losses)) if all_losses
               else float("nan"),
               "per_worker": stats,
               "model": master}  # the trained master (parent-side)
        if debug:
            print(f"ProcessMultiTrainer: {out['batches']} batches / "
                  f"{updates} dense updates over {self.process_num} "
                  f"processes, mean loss {out['loss_mean']:.6f}")
        return out
