"""MultiSlot data generators: user ETL → the dataset text protocol.

Reference: python/paddle/distributed/fleet/data_generator/
data_generator.py (DataGenerator:20, MultiSlotDataGenerator:224,
MultiSlotStringDataGenerator:180): users subclass, implement
``generate_sample(line)`` returning an iterator of
[(slot_name, [values...]), ...] per sample, and the generator formats
the MultiSlot text lines that QueueDataset/InMemoryDataset (and the
trainer's slot parser) consume:

    <slot_len> v1 v2 ... <slot_len> v1 ...    (values form)
    name:<len> ...                            (the reference keeps the
                                               id order per line)

``run_from_stdin`` is the pipe-command entry the reference installs
into dataset.set_pipe_command; ``run_from_memory`` drains
``generate_sample(None)`` for in-memory construction.
"""

from __future__ import annotations

import sys
from typing import Iterable, Iterator, List, Tuple

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class DataGenerator:
    def __init__(self):
        self._proto_info = None
        self.batch_size_ = 32

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    # -- user hooks ---------------------------------------------------------
    def generate_sample(self, line):
        """Override: yield one or more samples for ``line`` — each a
        list of (slot_name, values) pairs."""
        raise NotImplementedError(
            "subclass DataGenerator and implement generate_sample "
            "(reference data_generator.py:137)")

    def generate_batch(self, samples):
        """Override to batch-process; default passthrough (reference
        :158)."""
        def local_iter():
            for s in samples:
                yield s
        return local_iter

    # -- drivers ------------------------------------------------------------
    def _emit_batched(self, samples, sink):
        """Apply generate_batch per ``batch_size_`` window (the
        reference's run loop applies the batch hook before
        serialization)."""
        buf = []

        def flush():
            for s in self.generate_batch(list(buf))():
                if s is not None:
                    sink(self._gen_str(s))
            buf.clear()
        for s in samples:
            if s is None:
                continue
            buf.append(s)
            if len(buf) == self.batch_size_:
                flush()
        if buf:
            flush()

    def run_from_stdin(self):
        """Read lines from stdin, write protocol lines to stdout (the
        dataset pipe-command contract)."""
        def gen():
            for line in sys.stdin:
                for sample in self.generate_sample(line)():
                    yield sample
        self._emit_batched(gen(), sys.stdout.write)

    def run_from_memory(self):
        """Drain generate_sample(None); returns the protocol lines."""
        out = []
        self._emit_batched(self.generate_sample(None)(), out.append)
        return out

    def _gen_str(self, sample):
        raise NotImplementedError(
            "use MultiSlotDataGenerator or "
            "MultiSlotStringDataGenerator (reference :175)")


class MultiSlotStringDataGenerator(DataGenerator):
    def _gen_str(self, sample) -> str:
        """[(name, [str values])...] → '<len> v ...' joined
        (reference :180 — values emitted as-is)."""
        parts: List[str] = []
        for _, values in sample:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts) + "\n"


class MultiSlotDataGenerator(DataGenerator):
    def _gen_str(self, sample) -> str:
        """Typed form (reference :224): validates that every line
        carries the same slots in the same order; values int or
        float."""
        parts: List[str] = []
        names = []
        for name, values in sample:
            names.append(name)
            if not values:
                raise ValueError(
                    f"slot {name!r} has no values (reference "
                    "data_generator check)")
            parts.append(str(len(values)))
            for v in values:
                if not isinstance(v, (int, float)):
                    raise ValueError(
                        f"slot {name!r} value {v!r} is not int/float")
                parts.append(str(v))
        if self._proto_info is None:
            self._proto_info = names
        elif names != self._proto_info:
            raise ValueError(
                "sample slots changed between lines: "
                f"{names} vs {self._proto_info} (the reference "
                "enforces a stable slot order)")
        return " ".join(parts) + "\n"
