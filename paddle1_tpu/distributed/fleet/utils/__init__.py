"""fleet.utils (reference python/paddle/distributed/fleet/utils/)."""

from . import fs
from . import recompute as _recompute_mod
from .fs import HDFSClient, LocalFS
from .recompute import recompute, recompute_sequential


class _FleetUtil:
    """fleet.util facade (reference fleet/base/util_factory.py): barrier /
    all-reduce helpers over the coordination service."""

    def barrier(self, comm_world: str = "worker"):
        from ...collective import barrier
        barrier()

    def all_reduce(self, input, mode: str = "sum", comm_world: str = "worker"):
        return input  # single-controller: reduction over hosts is in-graph

    def get_file_shard(self, files):
        from ... import env
        n = env.get_world_size()
        i = env.get_rank()
        return files[i::n]


fleet_util = _FleetUtil()

__all__ = ["recompute", "recompute_sequential", "fleet_util", "fs",
           "LocalFS", "HDFSClient"]
