"""Activation recomputation (gradient checkpointing).

Analog of the reference's ``RecomputeFunction``
(python/paddle/distributed/fleet/utils/recompute.py:63): a PyLayer that
drops intermediate activations in forward and re-runs the segment (with the
saved RNG state) inside backward.

TPU-native: ``jax.checkpoint`` is exactly this transform, with XLA doing the
re-forward inside the compiled backward, so the implementation collapses to
wrapping the segment's pure function. RNG parity (reference saves/restores
CUDA seeds, recompute.py:88-114) comes for free: the segment's dropout keys
are explicit inputs, so the re-forward reuses identical keys.
"""

from __future__ import annotations

from typing import Any, Callable

import jax

from ....autograd.engine import apply
from ....core.generator import next_key, rng_scope
from ....core.tensor import Tensor
from ....nn.layer_base import Layer

__all__ = ["recompute", "recompute_sequential"]


def recompute(function: Callable, *args, **kwargs):
    """Run ``function(*args)`` without keeping its internal activations;
    backward re-executes it (reference recompute.py:162 recompute())."""
    preserve = kwargs.pop("preserve_rng_state", True)
    use_reentrant = kwargs.pop("use_reentrant", True)
    if kwargs:
        raise TypeError(f"recompute got unexpected kwargs {list(kwargs)}")

    layer = function if isinstance(function, Layer) else None
    bound_self = getattr(function, "__self__", None)
    bound_method = None
    if layer is None and isinstance(bound_self, Layer):
        layer = bound_self      # bound method of a Layer: params threadable
        bound_method = function  # may be forward or any other method
    key = next_key()

    # split args into traced tensors and static (non-tensor) values,
    # preserving positions so the segment sees the original signature
    tensor_pos = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
    tensor_args = [args[i] for i in tensor_pos]

    def _rebuild_args(arrays):
        full = list(args)
        for pos, arr in zip(tensor_pos, arrays):
            full[pos] = Tensor(arr, stop_gradient=True)
        return full

    fwd_callable = (bound_method if bound_method is not None else
                    layer.forward if layer is not None else function)

    if layer is not None:
        names = list(layer.functional_state().keys())
        params = [layer.state_dict()[n] for n in names]

        @jax.checkpoint
        def seg(key, param_arrays, *input_arrays):
            with rng_scope(key):
                with layer.load_functional_state(
                        dict(zip(names, param_arrays))):
                    out = fwd_callable(*_rebuild_args(input_arrays))
                    return (tuple(t.data for t in out)
                            if isinstance(out, (tuple, list))
                            else out.data)

        def op(*flat):
            p = list(flat[:len(params)])
            x = flat[len(params):]
            return seg(key, p, *x)

        return apply("recompute", op, tuple(params + tensor_args))

    # Opaque callable: parameters it closes over cannot be threaded into
    # jax.checkpoint as differentiable inputs, and capturing them as trace
    # constants would SILENTLY drop their gradients. Run the segment on the
    # normal tape instead — correct grads, no memory saving — and say so.
    import warnings
    warnings.warn(
        "recompute() got an opaque callable; cannot prove it uses no layer "
        "parameters, so activations are NOT discarded (gradients stay "
        "correct). Pass the Layer itself (or its bound .forward) to get "
        "actual recomputation.", stacklevel=2)
    return function(*args)


def recompute_sequential(ctx: dict, functions, *args):
    """Recompute over a Sequential in ``segments`` chunks (reference
    recompute_sequential / recompute_hybrid)."""
    segments = ctx.get("segments", 1)
    layers = list(functions)
    per = max(1, len(layers) // segments)
    x = args[0] if len(args) == 1 else args
    for i in range(0, len(layers), per):
        chunk = layers[i:i + per]

        class _Seg(Layer):
            def __init__(self, ls):
                super().__init__()
                from ....nn.layer_norm_act import LayerList
                self.ls = LayerList(ls)

            def forward(self, x):
                for l in self.ls:
                    x = l(x)
                return x

        x = recompute(_Seg(chunk), x)
    return x
