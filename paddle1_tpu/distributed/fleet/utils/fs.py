"""Filesystem abstraction: LocalFS + HDFSClient.

Analog of the reference's
/root/reference/python/paddle/distributed/fleet/utils/fs.py (FS base,
LocalFS, HDFSClient driving the ``hadoop fs`` CLI with retries) and the
C++ side /root/reference/paddle/fluid/framework/io/fs.cc. Checkpoints and
fleet utilities write through this interface so a cluster deployment can
point them at HDFS (or any hadoop-compatible store) without code changes.

TPU-native note: on Cloud TPU pods the idiomatic remote store is GCS via
a mounted path or gcsfuse — LocalFS covers that transparently; HDFSClient
keeps the reference's on-prem contract.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import time
from typing import List, Optional, Tuple

from ....core.errors import PreconditionNotMetError

__all__ = ["ExecuteError", "FSFileExistsError", "FSFileNotExistsError",
           "FSTimeOut", "FS", "LocalFS", "HDFSClient"]


class ExecuteError(Exception):
    pass


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class FSTimeOut(Exception):
    pass


class FS:
    """Interface (reference fs.py FS abstract base)."""

    def ls_dir(self, fs_path):
        raise NotImplementedError

    def is_file(self, fs_path):
        raise NotImplementedError

    def is_dir(self, fs_path):
        raise NotImplementedError

    def is_exist(self, fs_path):
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, fs_path):
        raise NotImplementedError

    def delete(self, fs_path):
        raise NotImplementedError

    def need_upload_download(self):
        raise NotImplementedError

    def rename(self, fs_src_path, fs_dst_path):
        raise NotImplementedError

    def mv(self, fs_src_path, fs_dst_path, overwrite=False):
        raise NotImplementedError

    def upload_dir(self, local_dir, dest_dir):
        raise NotImplementedError

    def list_dirs(self, fs_path):
        raise NotImplementedError

    def touch(self, fs_path, exist_ok=True):
        raise NotImplementedError


class LocalFS(FS):
    """Local (or mounted-remote, e.g. gcsfuse) filesystem — reference
    fs.py LocalFS."""

    def ls_dir(self, fs_path) -> Tuple[List[str], List[str]]:
        """Returns (dirs, files) — the reference's pair contract."""
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for f in sorted(os.listdir(fs_path)):
            (dirs if os.path.isdir(os.path.join(fs_path, f))
             else files).append(f)
        return dirs, files

    def mkdirs(self, fs_path):
        assert not os.path.isfile(fs_path), f"{fs_path} is already a file"
        os.makedirs(fs_path, exist_ok=True)

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def _rmr(self, fs_path):
        shutil.rmtree(fs_path)

    def _rm(self, fs_path):
        os.remove(fs_path)

    def delete(self, fs_path):
        if not self.is_exist(fs_path):
            return
        if os.path.isfile(fs_path):
            return self._rm(fs_path)
        return self._rmr(fs_path)

    def need_upload_download(self) -> bool:
        return False

    def is_file(self, fs_path) -> bool:
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path) -> bool:
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path) -> bool:
        return os.path.exists(fs_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if exist_ok:
                return
            raise FSFileExistsError(fs_path)
        with open(fs_path, "a"):
            pass

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        if not self.is_exist(src_path):
            raise FSFileNotExistsError(src_path)
        if overwrite and self.is_exist(dst_path):
            self.delete(dst_path)
        if self.is_exist(dst_path):
            raise FSFileExistsError(dst_path)
        os.rename(src_path, dst_path)

    def list_dirs(self, fs_path) -> List[str]:
        if not self.is_exist(fs_path):
            return []
        return [f for f in sorted(os.listdir(fs_path))
                if os.path.isdir(os.path.join(fs_path, f))]

    def upload(self, local_path, fs_path):
        # local → local: a copy (mounted-remote case)
        if os.path.isdir(local_path):
            shutil.copytree(local_path, fs_path)
        else:
            shutil.copy2(local_path, fs_path)

    def download(self, fs_path, local_path):
        self.upload(fs_path, local_path)


class HDFSClient(FS):
    """``hadoop fs`` CLI driver (reference fs.py HDFSClient: every call
    shells out with configured retries; reference fs.cc does the same from
    C++)."""

    def __init__(self, hadoop_home: Optional[str] = None,
                 configs: Optional[dict] = None, time_out: int = 5 * 60,
                 sleep_inter: int = 1000, retry_times: int = 3):
        self._hadoop_home = hadoop_home or os.environ.get("HADOOP_HOME")
        self._configs = configs or {}
        self._time_out = time_out
        self._sleep_s = sleep_inter / 1000.0
        self._retries = retry_times
        bin_path = (os.path.join(self._hadoop_home, "bin", "hadoop")
                    if self._hadoop_home else shutil.which("hadoop"))
        if bin_path is None or not os.path.exists(bin_path):
            raise PreconditionNotMetError(
                "HDFSClient needs the hadoop CLI: pass hadoop_home= or set "
                "HADOOP_HOME (reference fs.py requires the same). For "
                "GCS-style remote storage on TPU pods, mount the bucket "
                "and use LocalFS.")
        self._bin = bin_path

    def _run(self, *cmd) -> Tuple[int, str]:
        full = [self._bin, "fs"]
        for k, v in self._configs.items():
            full += ["-D", f"{k}={v}"]
        full += list(cmd)
        last = ""
        for attempt in range(self._retries):
            try:
                r = subprocess.run(full, capture_output=True, text=True,
                                   timeout=self._time_out)
            except subprocess.TimeoutExpired as e:
                raise FSTimeOut(f"{' '.join(full)} timed out") from e
            if r.returncode == 0:
                return 0, r.stdout
            last = r.stderr
            if attempt + 1 < self._retries:  # no dead sleep after the last
                time.sleep(self._sleep_s)
        raise ExecuteError(f"{' '.join(full)} failed after "
                           f"{self._retries} tries: {last}")

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        _, out = self._run("-ls", fs_path)
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def is_exist(self, fs_path) -> bool:
        try:
            self._run("-test", "-e", fs_path)
            return True
        except ExecuteError:
            return False

    def is_dir(self, fs_path) -> bool:
        try:
            self._run("-test", "-d", fs_path)
            return True
        except ExecuteError:
            return False

    def is_file(self, fs_path) -> bool:
        return self.is_exist(fs_path) and not self.is_dir(fs_path)

    def upload(self, local_path, fs_path):
        self._run("-put", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)

    def mkdirs(self, fs_path):
        self._run("-mkdir", "-p", fs_path)

    def delete(self, fs_path):
        if self.is_exist(fs_path):
            self._run("-rm", "-r", fs_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if exist_ok:
                return
            raise FSFileExistsError(fs_path)
        self._run("-touchz", fs_path)

    def mv(self, fs_src_path, fs_dst_path, overwrite=False):
        if overwrite and self.is_exist(fs_dst_path):
            self.delete(fs_dst_path)
        self._run("-mv", fs_src_path, fs_dst_path)

    def list_dirs(self, fs_path) -> List[str]:
        return self.ls_dir(fs_path)[0]

    def need_upload_download(self) -> bool:
        return True
