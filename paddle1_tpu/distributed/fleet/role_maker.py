"""Role makers: who am I in the job?

Analog of the reference's ``PaddleCloudRoleMaker``/``UserDefinedRoleMaker``
(python/paddle/distributed/fleet/base/role_maker.py) which parse the launcher
env protocol (PADDLE_TRAINER_ID, PADDLE_TRAINERS_NUM,
PADDLE_TRAINER_ENDPOINTS, TRAINING_ROLE…). The TPU build keeps the same env
protocol so `paddle1_tpu.distributed.launch` scripts port unchanged; the PS
roles (server/heter) are accepted but collective is the primary mode.
"""

from __future__ import annotations

import os
from typing import List, Optional

__all__ = ["Role", "PaddleCloudRoleMaker", "UserDefinedRoleMaker"]


class Role:
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class RoleMakerBase:
    def __init__(self):
        self._is_collective = True

    def is_worker(self) -> bool:
        return True

    def is_server(self) -> bool:
        return False

    def is_first_worker(self) -> bool:
        return self.worker_index() == 0

    def worker_num(self) -> int:
        raise NotImplementedError

    def worker_index(self) -> int:
        raise NotImplementedError

    def server_num(self) -> int:
        return 0

    def server_index(self) -> int:
        return -1

    def role_id(self) -> int:
        return self.worker_index()

    def get_trainer_endpoints(self) -> List[str]:
        return []

    def _barrier(self, comm_world=None):
        from ..collective import barrier
        barrier()


class PaddleCloudRoleMaker(RoleMakerBase):
    """Parses the launcher's env protocol (reference role_maker.py:946LoC
    class; env names at launch_utils.py:452 start_local_trainers)."""

    def __init__(self, is_collective: bool = True, **kwargs):
        super().__init__()
        self._is_collective = is_collective
        self._role = (Role.SERVER
                      if os.environ.get("TRAINING_ROLE", "TRAINER").upper()
                      == "PSERVER" else Role.WORKER)

    def is_worker(self) -> bool:
        return self._role == Role.WORKER

    def is_server(self) -> bool:
        return self._role == Role.SERVER

    def worker_num(self) -> int:
        return int(os.environ.get(
            "PADDLE_TRAINERS_NUM", os.environ.get("WORLD_SIZE", "1")))

    def worker_index(self) -> int:
        return int(os.environ.get(
            "PADDLE_TRAINER_ID", os.environ.get("RANK", "0")))

    def get_trainer_endpoints(self) -> List[str]:
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else ["127.0.0.1:6170"]


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """Explicit role assignment (reference UserDefinedRoleMaker)."""

    def __init__(self, is_collective: bool = True, current_id: int = 0,
                 worker_num: int = 1, role: int = Role.WORKER,
                 worker_endpoints: Optional[List[str]] = None, **kwargs):
        super().__init__(is_collective=is_collective)
        self._current_id = current_id
        self._worker_num = worker_num
        self._role = role
        self._worker_endpoints = worker_endpoints or ["127.0.0.1:6170"]

    def worker_num(self) -> int:
        return self._worker_num

    def worker_index(self) -> int:
        return self._current_id

    def get_trainer_endpoints(self) -> List[str]:
        return list(self._worker_endpoints)
