"""paddle1_tpu.distributed.fleet — the distributed-training façade
(reference python/paddle/distributed/fleet/).

Usage matches the reference:

    import paddle1_tpu.distributed.fleet as fleet
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 2}
    fleet.init(is_collective=True, strategy=strategy)
    model = fleet.distributed_model(model)
    opt = fleet.distributed_optimizer(opt)
"""

from .strategy import DistributedStrategy
from .role_maker import PaddleCloudRoleMaker, UserDefinedRoleMaker, Role
from .fleet_base import Fleet, fleet
from .meta_optimizers import (DGCMomentumOptimizer, LocalSGDOptimizer,
                              compile_strategy)
from ..meta_parallel import (ColumnParallelLinear, RowParallelLinear,
                             VocabParallelEmbedding, ParallelCrossEntropy,
                             LayerDesc, SharedLayerDesc, PipelineLayer,
                             SegmentLayers)
from .utils import recompute, fleet_util
from .trainer import (HogwildWorker, InferWorker, MultiTrainer,
                      TrainerDesc, DeviceWorkerDesc, create_trainer)
from .process_trainer import ProcessMultiTrainer
from .data_generator import (DataGenerator, MultiSlotDataGenerator,
                             MultiSlotStringDataGenerator)
from ..topology import CommunicateTopology, HybridCommunicateGroup
from ...io.file_dataset import (DatasetBase, InMemoryDataset,
                                QueueDataset)
# the reference exposes the util singleton's class as UtilBase
# (fleet_util imported above)
UtilBase = type(fleet_util)

# module-level delegation to the singleton (the reference exposes
# fleet.init etc. as module functions)
init = fleet.init
parallel_engine = fleet.parallel_engine
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
distributed_scaler = fleet.distributed_scaler
minimize = fleet.minimize
is_first_worker = fleet.is_first_worker
worker_index = fleet.worker_index
worker_num = fleet.worker_num
is_worker = fleet.is_worker
is_server = fleet.is_server
worker_endpoints = fleet.worker_endpoints
barrier_worker = fleet.barrier_worker
init_worker = fleet.init_worker
init_server = fleet.init_server
run_server = fleet.run_server
stop_worker = fleet.stop_worker
get_hybrid_communicate_group = fleet.get_hybrid_communicate_group

__all__ = ["DistributedStrategy", "PaddleCloudRoleMaker",
           "UserDefinedRoleMaker", "Role", "Fleet", "fleet", "init",
           "distributed_model", "distributed_optimizer", "minimize",
           "recompute", "fleet_util", "ColumnParallelLinear",
           "RowParallelLinear", "VocabParallelEmbedding",
           "ParallelCrossEntropy", "LayerDesc", "SharedLayerDesc",
           "PipelineLayer", "SegmentLayers"]
