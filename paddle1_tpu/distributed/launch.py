"""Process launcher — ``python -m paddle1_tpu.distributed.launch train.py``.

Analog of the reference launcher (python/paddle/distributed/fleet/launch.py:
217 launch_collective, :364 launch; launch_utils.py:452 start_local_trainers
sets PADDLE_TRAINER_ID/PADDLE_CURRENT_ENDPOINT/... per subprocess, :559
watch_local_trainers kills the pod on any death).

TPU-native: one process per *host* (not per chip) — XLA drives every local
chip from a single process, so on a single host the launcher mostly execs
the script directly. Multi-host TPU pods get one process per host with the
JAX coordination-service env; the watch loop keeps the reference's
fail-fast-and-kill-all semantics.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

__all__ = ["launch", "main"]


def _parse_args(argv):
    p = argparse.ArgumentParser("paddle1_tpu.distributed.launch")
    p.add_argument("--nnodes", type=int,
                   default=int(os.environ.get("PADDLE_NNODES", "1")),
                   help="number of hosts")
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", "0")))
    p.add_argument("--master", type=str,
                   default=os.environ.get("PADDLE_MASTER", "127.0.0.1:6170"),
                   help="coordinator host:port")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per host (1 per host is TPU-idiomatic; "
                        ">1 only for CPU-simulated multi-rank testing)")
    p.add_argument("--ips", type=str, default=None,
                   help="comma-separated host list (reference flag)")
    p.add_argument("--gpus", "--devices", dest="devices", type=str,
                   default=None, help="accepted for compat; TPU chips are "
                   "managed by XLA, not per-process pinning")
    p.add_argument("--log_dir", type=str, default=None)
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def launch(argv: Optional[List[str]] = None):
    from .launch_utils import (get_cluster, start_local_trainers,
                               watch_local_trainers)
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    nproc = args.nproc_per_node
    host, port = (args.master.split(":") + ["6170"])[:2]
    if args.nnodes <= 1 and nproc <= 1:
        # single host, single process: exec in place (XLA owns all chips)
        env = dict(os.environ)
        env.setdefault("PADDLE_TRAINER_ID", "0")
        env.setdefault("PADDLE_TRAINERS_NUM", "1")
        os.execve(sys.executable,
                  [sys.executable, "-u", args.training_script] +
                  args.training_script_args, env)
    # Cluster/Pod model (reference launch_utils.py:58): --ips names the
    # hosts; this invocation starts only its OWN pod's trainers, exactly
    # like the reference (each host runs the same launch command with its
    # node_rank). Single-host multi-proc testing uses one pod with
    # nproc_per_node trainers.
    node_ips = (args.ips.split(",") if args.ips else [host])
    if len(node_ips) != args.nnodes:
        if args.ips:
            raise SystemExit(
                f"--ips lists {len(node_ips)} hosts but --nnodes="
                f"{args.nnodes}")
        node_ips = [host] * args.nnodes  # local simulation of N nodes
    cluster = get_cluster(node_ips, nproc, base_port=int(port))
    if args.ips is None and args.nnodes > 1 and \
            host in ("127.0.0.1", "localhost"):
        # loopback master + no host list = local N-node simulation: this
        # one command hosts EVERY pod (reference test_dist_base-style
        # virtual cluster). A real multi-host run names a shared master
        # (or --ips) and spawns only its own --node_rank pod below.
        pods = cluster.pods
    else:
        pods = [cluster.pod(args.node_rank)]
    procs = []
    for pod in pods:
        procs.extend(start_local_trainers(
            cluster, pod, args.training_script, args.training_script_args,
            log_dir=args.log_dir))
    rc = watch_local_trainers(procs)
    if rc != 0:
        sys.exit(rc)


def main():
    launch()


if __name__ == "__main__":
    main()
