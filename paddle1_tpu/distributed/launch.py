"""Process launcher — ``python -m paddle1_tpu.distributed.launch train.py``.

Analog of the reference launcher (python/paddle/distributed/fleet/launch.py:
217 launch_collective, :364 launch; launch_utils.py:452 start_local_trainers
sets PADDLE_TRAINER_ID/PADDLE_CURRENT_ENDPOINT/... per subprocess, :559
watch_local_trainers kills the pod on any death).

TPU-native: one process per *host* (not per chip) — XLA drives every local
chip from a single process, so on a single host the launcher mostly execs
the script directly. Multi-host TPU pods get one process per host with the
JAX coordination-service env; the watch loop keeps the reference's
fail-fast-and-kill-all semantics.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

__all__ = ["launch", "main"]


def _parse_args(argv):
    p = argparse.ArgumentParser("paddle1_tpu.distributed.launch")
    p.add_argument("--nnodes", type=int,
                   default=int(os.environ.get("PADDLE_NNODES", "1")),
                   help="number of hosts")
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", "0")))
    p.add_argument("--master", type=str,
                   default=os.environ.get("PADDLE_MASTER", "127.0.0.1:6170"),
                   help="coordinator host:port")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per host (1 per host is TPU-idiomatic; "
                        ">1 only for CPU-simulated multi-rank testing)")
    p.add_argument("--ips", type=str, default=None,
                   help="comma-separated host list (reference flag)")
    p.add_argument("--gpus", "--devices", dest="devices", type=str,
                   default=None, help="accepted for compat; TPU chips are "
                   "managed by XLA, not per-process pinning")
    p.add_argument("--log_dir", type=str, default=None)
    # elastic supervision (ft_* flag family; see distributed/supervisor)
    p.add_argument("--ft_supervise", type=str, default=None,
                   choices=["off", "fail_fast", "restart", "drain",
                            "resize"],
                   help="supervise workers with heartbeats + hang "
                        "detection and respond per policy: fail_fast "
                        "(kill the pod), restart (relaunch the failed "
                        "rank, which resumes from its last committed "
                        "checkpoint; in a multi-worker world a failure "
                        "routes into the resize path instead), drain "
                        "(graceful checkpoint-and-stop), resize "
                        "(elastic: drain survivors, reshard the "
                        "checkpoint to the new world size, relaunch — "
                        "see FLAGS_ft_elastic_min_world / "
                        "FLAGS_ft_max_resizes). Default: the "
                        "FLAGS_ft_supervise flag (empty = plain "
                        "fail-fast watch, no heartbeats)")
    p.add_argument("--ft_hang_timeout", type=float, default=None,
                   help="seconds without a worker heartbeat before it "
                        "is declared hung (default: FLAGS_ft_hang_timeout)")
    p.add_argument("--ft_max_worker_restarts", type=int, default=None,
                   help="per-rank relaunch budget under restart policy "
                        "(default: FLAGS_ft_max_worker_restarts)")
    # parameter-server mode (reference launch.py:278): the script serves
    # both roles, branching on TRAINING_ROLE
    p.add_argument("--server_num", type=int, default=0,
                   help="PS mode: number of table-server processes")
    p.add_argument("--servers", type=str, default="",
                   help="PS mode: explicit server host:port list "
                        "(overrides --server_num)")
    p.add_argument("--trainer_num", type=int, default=None,
                   help="PS mode: number of trainer processes "
                        "(default: nproc_per_node)")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def launch(argv: Optional[List[str]] = None):
    from ..core import flags as core_flags
    from .launch_utils import (get_cluster, start_local_trainers,
                               watch_local_trainers)
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    nproc = args.nproc_per_node
    host, port = (args.master.split(":") + ["6170"])[:2]
    supervise = (args.ft_supervise if args.ft_supervise is not None
                 else core_flags.flag("ft_supervise"))
    supervise = "" if supervise == "off" else supervise
    if args.server_num > 0 or args.servers:
        if supervise:
            import warnings
            warnings.warn(
                "--ft_supervise is not supported in parameter-server "
                "mode yet: PS jobs keep the legacy exit-only watch "
                "(no heartbeats, hang detection, or restart)")
        from .launch_utils import start_ps_procs, watch_ps_procs
        n_trainers = (args.trainer_num if args.trainer_num is not None
                      else nproc)
        if args.servers:
            server_eps = args.servers.split(",")
            # multi-node PS: this node hosts only the servers bound to its
            # own address, and its trainers get globally-unique ids
            # (reference launch_utils start_pservers: per-node filtering)
            if args.nnodes > 1 and not args.ips:
                raise SystemExit(
                    "multi-node PS needs --ips so each node knows its own "
                    "address (the master host is only correct for node 0)")
            my_ip = (args.ips.split(",")[args.node_rank] if args.ips
                     else host)
            local_server_eps = [ep for ep in server_eps
                                if ep.rsplit(":", 1)[0] == my_ip] \
                if args.nnodes > 1 else server_eps
            trainer_id_base = args.node_rank * n_trainers
            total_trainers = args.nnodes * n_trainers
        elif args.nnodes > 1:
            raise SystemExit(
                "PS mode across nodes needs the explicit --servers "
                "host:port list (each node must know every server and "
                "which ones are its own); --server_num alone is "
                "single-node")
        else:
            base = int(port) + 1000  # clear of the trainer port block
            server_eps = [f"{host}:{base + i}"
                          for i in range(args.server_num)]
            local_server_eps = server_eps
            trainer_id_base, total_trainers = 0, n_trainers
        servers, trainers = start_ps_procs(
            server_eps, n_trainers, args.training_script,
            args.training_script_args, log_dir=args.log_dir,
            local_server_endpoints=local_server_eps,
            trainer_id_base=trainer_id_base,
            total_trainers=total_trainers)
        rc = watch_ps_procs(servers, trainers)
        if rc != 0:
            sys.exit(rc)
        return
    if args.nnodes <= 1 and nproc <= 1 and not supervise:
        # single host, single process: exec in place (XLA owns all
        # chips). A supervised single process can NOT exec in place —
        # the supervisor must outlive the worker to restart it, so it
        # falls through to the subprocess path below.
        env = dict(os.environ)
        env.setdefault("PADDLE_TRAINER_ID", "0")
        env.setdefault("PADDLE_TRAINERS_NUM", "1")
        os.execve(sys.executable,
                  [sys.executable, "-u", args.training_script] +
                  args.training_script_args, env)
    # Cluster/Pod model (reference launch_utils.py:58): --ips names the
    # hosts; this invocation starts only its OWN pod's trainers, exactly
    # like the reference (each host runs the same launch command with its
    # node_rank). Single-host multi-proc testing uses one pod with
    # nproc_per_node trainers.
    node_ips = (args.ips.split(",") if args.ips else [host])
    if len(node_ips) != args.nnodes:
        if args.ips:
            raise SystemExit(
                f"--ips lists {len(node_ips)} hosts but --nnodes="
                f"{args.nnodes}")
        node_ips = [host] * args.nnodes  # local simulation of N nodes
    cluster = get_cluster(node_ips, nproc, base_port=int(port))
    if args.ips is None and args.nnodes > 1 and \
            host in ("127.0.0.1", "localhost"):
        # loopback master + no host list = local N-node simulation: this
        # one command hosts EVERY pod (reference test_dist_base-style
        # virtual cluster). A real multi-host run names a shared master
        # (or --ips) and spawns only its own --node_rank pod below.
        pods = cluster.pods
    else:
        pods = [cluster.pod(args.node_rank)]
    if supervise:
        # the Supervisor owns spawn (heartbeat env protocol + respawn
        # spec) and the watch loop (hang detection, policy response).
        # restart in a multi-worker world is no longer the PR 3 dead
        # end (an individual rank cannot rejoin live jax.distributed
        # collectives): on a SINGLE-node pod the Supervisor routes such
        # failures into the elastic RESIZE path — drain survivors,
        # reshard, relaunch at the smaller world. Elasticity needs one
        # supervisor owning every rank (numbered 0..world-1): a
        # per-node supervisor of a multi-node pod only sees its slice,
        # so resize semantics are disabled there.
        single_pod = args.nnodes <= 1
        if supervise == "resize" and not single_pod:
            raise SystemExit(
                "--ft_supervise resize needs the single-node launcher "
                "(one Supervisor owning every rank): each node's "
                "supervisor only sees its own slice of the global "
                "ranks and cannot rebuild the world. Run nnodes=1, or "
                "drive elasticity from the cluster scheduler "
                "(Supervisor.request_resize on the node that owns the "
                "whole fleet)")
        if supervise == "restart" and not single_pod and \
                cluster.world_size() > 1:
            import warnings
            warnings.warn(
                "ft_supervise=restart on a multi-NODE pod relaunches "
                "INDIVIDUAL ranks, which cannot rejoin live "
                "jax.distributed collectives — the per-node supervisor "
                "cannot resize a world it only partly owns. Use "
                "restart for independent workers; collective pods want "
                "fail_fast (outer scheduler retry), drain, or a "
                "single-node resize job")

        def _elastic_env(rank, new_world):
            # the SAME per-rank env block start_local_trainers stamps
            # (launch_utils.trainer_env — one source of truth), rebuilt
            # over a cluster of the new world size: stale endpoint
            # lists / device pins on a relaunched or cloned rank would
            # collide
            from .launch_utils import trainer_env
            c = get_cluster([host], new_world, base_port=int(port))
            new_pod = c.pod(0)
            return trainer_env(c, new_pod, new_pod.trainers[rank])

        from .supervisor import Supervisor
        sup = Supervisor(policy=supervise,
                         hang_timeout=args.ft_hang_timeout,
                         max_restarts=args.ft_max_worker_restarts,
                         log_dir=args.log_dir,
                         elastic=None if single_pod else False,
                         resize_env_hook=(_elastic_env if single_pod
                                          else None))
        for pod in pods:
            start_local_trainers(
                cluster, pod, args.training_script,
                args.training_script_args, log_dir=args.log_dir,
                supervisor=sup)
        rc = sup.run()
        if rc != 0:
            sys.exit(rc)
        return
    procs = []
    for pod in pods:
        procs.extend(start_local_trainers(
            cluster, pod, args.training_script, args.training_script_args,
            log_dir=args.log_dir))
    rc = watch_local_trainers(procs)
    if rc != 0:
        sys.exit(rc)


def main():
    launch()


if __name__ == "__main__":
    main()
