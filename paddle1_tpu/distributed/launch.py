"""Process launcher — ``python -m paddle1_tpu.distributed.launch train.py``.

Analog of the reference launcher (python/paddle/distributed/fleet/launch.py:
217 launch_collective, :364 launch; launch_utils.py:452 start_local_trainers
sets PADDLE_TRAINER_ID/PADDLE_CURRENT_ENDPOINT/... per subprocess, :559
watch_local_trainers kills the pod on any death).

TPU-native: one process per *host* (not per chip) — XLA drives every local
chip from a single process, so on a single host the launcher mostly execs
the script directly. Multi-host TPU pods get one process per host with the
JAX coordination-service env; the watch loop keeps the reference's
fail-fast-and-kill-all semantics.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

__all__ = ["launch", "main"]


def _parse_args(argv):
    p = argparse.ArgumentParser("paddle1_tpu.distributed.launch")
    p.add_argument("--nnodes", type=int,
                   default=int(os.environ.get("PADDLE_NNODES", "1")),
                   help="number of hosts")
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", "0")))
    p.add_argument("--master", type=str,
                   default=os.environ.get("PADDLE_MASTER", "127.0.0.1:6170"),
                   help="coordinator host:port")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per host (1 per host is TPU-idiomatic; "
                        ">1 only for CPU-simulated multi-rank testing)")
    p.add_argument("--ips", type=str, default=None,
                   help="comma-separated host list (reference flag)")
    p.add_argument("--gpus", "--devices", dest="devices", type=str,
                   default=None, help="accepted for compat; TPU chips are "
                   "managed by XLA, not per-process pinning")
    p.add_argument("--log_dir", type=str, default=None)
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _spawn_one(rank: int, world: int, endpoints: List[str], args,
               extra_env=None):
    env = dict(os.environ)
    env.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
        "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
        "RANK": str(rank),
        "WORLD_SIZE": str(world),
        "FLAGS_selected_tpus": str(rank),
    })
    if extra_env:
        env.update(extra_env)
    stdout = None
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
        stdout = open(os.path.join(args.log_dir, f"workerlog.{rank}"), "w")
    cmd = [sys.executable, "-u", args.training_script] + \
        args.training_script_args
    return subprocess.Popen(cmd, env=env, stdout=stdout,
                            stderr=subprocess.STDOUT if stdout else None)


def _watch(procs):
    """Reference launch_utils.py:559: any death kills the pod, exit
    nonzero."""
    try:
        while True:
            alive = []
            for p in procs:
                ret = p.poll()
                if ret is None:
                    alive.append(p)
                elif ret != 0:
                    for q in procs:
                        if q.poll() is None:
                            q.send_signal(signal.SIGTERM)
                    sys.exit(ret)
            if not alive:
                return
            time.sleep(1)
    except KeyboardInterrupt:
        for q in procs:
            if q.poll() is None:
                q.send_signal(signal.SIGTERM)
        raise


def launch(argv: Optional[List[str]] = None):
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    nproc = args.nproc_per_node
    host, port = (args.master.split(":") + ["6170"])[:2]
    if args.nnodes <= 1 and nproc <= 1:
        # single host, single process: exec in place (XLA owns all chips)
        env = dict(os.environ)
        env.setdefault("PADDLE_TRAINER_ID", "0")
        env.setdefault("PADDLE_TRAINERS_NUM", "1")
        os.execve(sys.executable,
                  [sys.executable, "-u", args.training_script] +
                  args.training_script_args, env)
    world = args.nnodes * nproc
    endpoints = []
    for node in range(args.nnodes):
        h = host if args.ips is None else args.ips.split(",")[node]
        for i in range(nproc):
            endpoints.append(f"{h}:{int(port) + i}")
    procs = [
        _spawn_one(args.node_rank * nproc + i, world, endpoints, args)
        for i in range(nproc)
    ]
    _watch(procs)


def main():
    launch()


if __name__ == "__main__":
    main()
