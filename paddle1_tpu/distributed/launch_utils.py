"""Cluster/Pod/Trainer topology model for the launcher.

Analog of the reference's ``python/paddle/distributed/fleet/launch_utils.py``
(Cluster:58 / Pod / Trainer, get_cluster:141, start_local_trainers:452,
watch_local_trainers:559): the launcher builds an explicit cluster object
from the node list, spawns one worker per (pod, trainer) with the rank env
protocol, and a watch loop enforces fail-fast-kill-all.

TPU-native notes: a "trainer" is one *process* (driving all its local
chips via XLA), not one device; the coordination endpoint doubles as the
``jax.distributed`` coordinator that ``init_parallel_env`` dials.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

__all__ = ["Trainer", "Pod", "Cluster", "get_cluster",
           "start_local_trainers", "watch_local_trainers",
           "terminate_local_procs"]


class Trainer:
    """One worker process slot (reference launch_utils.py Trainer)."""

    def __init__(self, endpoint: str, rank: int,
                 accelerators: Optional[List[int]] = None):
        self.endpoint = endpoint
        self.rank = rank
        self.accelerators = accelerators or []

    def __repr__(self):
        return f"Trainer(rank={self.rank}, endpoint={self.endpoint!r})"


class Pod:
    """All trainers on one host (reference launch_utils.py Pod)."""

    def __init__(self, rank: int, addr: str):
        self.rank = rank
        self.addr = addr
        self.trainers: List[Trainer] = []

    @property
    def endpoints(self) -> List[str]:
        return [t.endpoint for t in self.trainers]

    def __repr__(self):
        return f"Pod(rank={self.rank}, addr={self.addr!r}, " \
               f"trainers={self.trainers})"


class Cluster:
    """The whole job (reference launch_utils.py Cluster)."""

    def __init__(self):
        self.pods: List[Pod] = []

    def trainers_endpoints(self) -> List[str]:
        return [t.endpoint for p in self.pods for t in p.trainers]

    def world_size(self) -> int:
        return sum(len(p.trainers) for p in self.pods)

    def pod(self, node_rank: int) -> Pod:
        return self.pods[node_rank]

    def __repr__(self):
        return f"Cluster(pods={self.pods})"


def get_cluster(node_ips: List[str], nproc_per_node: int,
                base_port: int = 6170) -> Cluster:
    """Build the Cluster from the host list (reference get_cluster:141:
    one Pod per ip, one Trainer per selected device — here per process).
    Ranks are assigned pod-major, matching the reference's endpoint
    ordering so PADDLE_TRAINER_ID == index into the endpoint list."""
    cluster = Cluster()
    rank = 0
    # distinct hosts reuse the same port block (the reference layout); a
    # repeated ip means a LOCAL multi-node simulation, where every rank
    # needs its own port
    local_sim = len(set(node_ips)) != len(node_ips)
    for node_rank, ip in enumerate(node_ips):
        pod = Pod(node_rank, ip)
        for i in range(nproc_per_node):
            off = rank if local_sim else i
            pod.trainers.append(Trainer(f"{ip}:{base_port + off}", rank))
            rank += 1
        cluster.pods.append(pod)
    return cluster


def trainer_env(cluster: Cluster, pod: Pod, trainer) -> dict:
    """The per-rank trainer env block (reference launch_utils env
    protocol) — the ONE place it is defined: the initial spawn
    (``start_local_trainers``) and the elastic resize relaunch
    (``launch.py``'s ``resize_env_hook``) both stamp exactly this."""
    endpoints = cluster.trainers_endpoints()
    world = cluster.world_size()
    return {
        "PADDLE_TRAINER_ID": str(trainer.rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_CURRENT_ENDPOINT": trainer.endpoint,
        "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
        "PADDLE_NODE_RANK": str(pod.rank),
        "PADDLE_NNODES": str(len(cluster.pods)),
        "RANK": str(trainer.rank),
        "WORLD_SIZE": str(world),
        "FLAGS_selected_tpus": str(trainer.rank),
    }


def start_local_trainers(cluster: Cluster, pod: Pod, training_script: str,
                         training_script_args: List[str],
                         log_dir: Optional[str] = None,
                         extra_env: Optional[dict] = None,
                         supervisor=None):
    """Spawn this pod's trainers (reference start_local_trainers:452 —
    same env protocol: PADDLE_TRAINER_ID/PADDLE_CURRENT_ENDPOINT/
    PADDLE_TRAINER_ENDPOINTS/PADDLE_TRAINERS_NUM, plus the coordination
    address init_parallel_env hands to jax.distributed.initialize).

    With ``supervisor`` (a :class:`~.supervisor.Supervisor`), trainers
    are *registered* instead of spawned directly — the supervisor owns
    the processes (it stamps the heartbeat env protocol and can
    relaunch a rank with the identical spec); returns ``[]`` and the
    caller runs ``supervisor.run()``. Without one, spawns plain Popen
    workers exactly as before."""
    procs = []
    for t in pod.trainers:
        env = dict(os.environ)
        env.update(trainer_env(cluster, pod, t))
        if extra_env:
            env.update(extra_env)
        cmd = [sys.executable, "-u", training_script] + \
            list(training_script_args)
        log_path = None
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            log_path = os.path.join(log_dir, f"workerlog.{t.rank}")
        if supervisor is not None:
            supervisor.add_worker(t.rank, cmd, env=env, log_path=log_path)
            continue
        stdout = open(log_path, "w") if log_path else None
        procs.append(subprocess.Popen(
            cmd, env=env, stdout=stdout,
            stderr=subprocess.STDOUT if stdout else None))
    return procs


def terminate_local_procs(procs) -> None:
    """Reference terminate_local_procs: SIGTERM the stragglers."""
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    deadline = time.time() + 10
    for p in procs:
        try:
            p.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            p.kill()


def watch_local_trainers(procs, poll_s: float = 1.0) -> int:
    """Reference watch_local_trainers:559: block until all trainers exit;
    the FIRST nonzero exit kills the rest and becomes the return code.
    Implemented by *adopting* the procs into a fail_fast
    :class:`~.supervisor.Supervisor` (exit-only watching: adopted
    processes have no heartbeat channel — ``launch --ft_supervise``
    gets the full hang/unhealthy detection by letting the supervisor
    own the spawn)."""
    if not procs:
        return 0  # nothing to watch (legacy loop fell through with 0)
    from .supervisor import Supervisor
    sup = Supervisor(policy="fail_fast", poll_s=poll_s)
    for i, p in enumerate(procs):
        sup.attach(i, p)
    return sup.run()


def start_ps_procs(server_endpoints: List[str], n_trainers: int,
                   training_script: str, training_script_args: List[str],
                   log_dir: Optional[str] = None,
                   local_server_endpoints: Optional[List[str]] = None,
                   trainer_id_base: int = 0,
                   total_trainers: Optional[int] = None):
    """Spawn PS servers + trainers (reference launch.py:278
    launch_ps / start_pservers+start_workers in launch_utils): each
    server gets TRAINING_ROLE=PSERVER and its own PADDLE_PORT; trainers
    get TRAINING_ROLE=TRAINER and the full server endpoint list. One
    user script serves both roles by branching on TRAINING_ROLE (the
    reference PS idiom)."""
    eps = ",".join(server_endpoints)

    def spawn(env_extra, tag):
        env = dict(os.environ)
        env["PADDLE_PSERVERS_IP_PORT_LIST"] = eps
        env.update(env_extra)
        stdout = None
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            stdout = open(os.path.join(log_dir, tag), "w")
        return subprocess.Popen(
            [sys.executable, "-u", training_script] +
            list(training_script_args), env=env, stdout=stdout,
            stderr=subprocess.STDOUT if stdout else None)

    local = (local_server_endpoints if local_server_endpoints is not None
             else server_endpoints)
    total = total_trainers if total_trainers is not None else n_trainers
    servers = []
    for i, ep in enumerate(server_endpoints):
        if ep not in local:
            continue  # another node's server (multi-node PS)
        host, port = ep.rsplit(":", 1)
        servers.append(spawn({"TRAINING_ROLE": "PSERVER",
                              "PADDLE_PORT": port, "POD_IP": host,
                              "PADDLE_SERVER_ID": str(i)},
                             f"serverlog.{i}"))
    trainers = []
    for r in range(n_trainers):
        gid = trainer_id_base + r
        trainers.append(spawn({"TRAINING_ROLE": "TRAINER",
                               "PADDLE_TRAINER_ID": str(gid),
                               "PADDLE_TRAINERS_NUM": str(total)},
                              f"workerlog.{gid}"))
    return servers, trainers


def watch_ps_procs(server_procs, trainer_procs, poll_s: float = 1.0) -> int:
    """PS watch semantics (reference launch_utils watch for PS mode): the
    job is DONE when every trainer exits 0 (servers are then torn down);
    any nonzero exit — or a server stopping while trainers still run —
    fails the job and kills everyone. Servers are *essential* workers of
    the :class:`~.supervisor.Supervisor`: any exit of theirs, clean or
    not, fails the job while trainers still run."""
    if not trainer_procs:
        # server-only node: the job IS the servers — block until they
        # exit, fail-fast on the first nonzero
        return watch_local_trainers(server_procs, poll_s)
    from .supervisor import Supervisor
    sup = Supervisor(policy="fail_fast", poll_s=poll_s)
    for i, p in enumerate(trainer_procs):
        sup.attach(i, p, role="trainer")
    for i, p in enumerate(server_procs):
        sup.attach(len(trainer_procs) + i, p, role="server",
                   essential=True)
    return sup.run()
