"""User-defined differentiable functions.

Analog of the reference's ``paddle.autograd.PyLayer``
(/root/reference/python/paddle/autograd/py_layer.py), used by recompute
(distributed/fleet/utils/recompute.py:63). The forward runs with the tape
disabled; a custom GradNode is installed whose vjp calls the user backward.
"""

from __future__ import annotations

from typing import Any, List, Tuple

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.errors import PreconditionNotMetError
from . import engine

__all__ = ["PyLayer", "PyLayerContext"]


class PyLayerContext:
    """ctx object handed to forward/backward for residual stashing."""

    def __init__(self):
        self._saved: Tuple[Tensor, ...] = ()
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    @property
    def saved_tensor(self):
        return list(self._saved)

    def saved_tensors(self):
        return list(self._saved)


class PyLayer:
    """Subclass with ``forward(ctx, *args)`` and ``backward(ctx, *grads)``
    staticmethods; call via ``MyLayer.apply(*args)``."""

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        with engine.no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outs, (tuple, list))
        out_list = [outs] if single else list(outs)

        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        needs_grad = engine.is_grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs)
        out_tensors = []
        for o in out_list:
            if isinstance(o, Tensor):
                t = Tensor(o.data, stop_gradient=not needs_grad)
            else:
                t = o  # non-tensor passthrough (kept out of the grad graph)
            out_tensors.append(t)

        if needs_grad:
            diff_inputs = [t for t in tensor_inputs if not t.stop_gradient]
            grad_outputs_mask = [isinstance(t, Tensor) for t in out_tensors]

            def vjp_fn(cotangents):
                if not isinstance(cotangents, (tuple, list)):
                    cotangents = (cotangents,)
                gts = []
                ci = 0
                for keep in grad_outputs_mask:
                    if keep:
                        gts.append(Tensor(cotangents[ci], stop_gradient=True))
                    ci += 1
                with engine.no_grad():
                    gins = cls.backward(ctx, *gts)
                if not isinstance(gins, (tuple, list)):
                    gins = (gins,)
                if len(gins) != len(diff_inputs):
                    raise PreconditionNotMetError(
                        f"{cls.__name__}.backward returned {len(gins)} grads "
                        f"for {len(diff_inputs)} differentiable inputs")
                return tuple(None if g is None else
                             (g.data if isinstance(g, Tensor) else g)
                             for g in gins)

            tensor_outs = [t for t in out_tensors if isinstance(t, Tensor)]
            in_edges = [(t._node, t._output_index, t) for t in diff_inputs]
            node = engine.GradNode(cls.__name__, vjp_fn, in_edges, tensor_outs)
            for j, ot in enumerate(tensor_outs):
                ot._node = node
                ot._output_index = j

        return out_tensors[0] if single else tuple(out_tensors)
