"""Tape/graph autograd engine for eager mode.

Analog of the reference's dygraph autograd: ``Tracer::TraceOp`` records a
``GradOpNode`` per executed op (/root/reference/paddle/fluid/imperative/
tracer.cc:133,207), ``BasicEngine`` executes the reverse graph with dependency
counting (imperative/basic_engine.cc:39,235,305), ``GradientAccumulator`` sums
fan-in gradients (gradient_accumulator.h:27), and ``PartialGradEngine``
implements ``paddle.grad`` (partial_grad_engine.cc).

TPU-native design: instead of per-op hand-written grad kernels, each eager op
is a pure jax function; when gradients are required we run it under
``jax.vjp`` and store the returned vjp closure on the grad node. XLA thus
provides every backward rule; the engine only does graph bookkeeping
(dependency counts, accumulation, hooks) — which is exactly the part of the
reference's BasicEngine that is not kernel dispatch.
"""

from __future__ import annotations

import threading
import weakref
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import flags
from ..core.errors import (InvalidArgumentError, PreconditionNotMetError,
                           ResourceExhaustedError)
from ..core.tensor import Tensor

__all__ = ["apply", "apply_custom_vjp", "run_backward", "grad", "no_grad",
           "enable_grad", "is_grad_enabled", "set_grad_enabled", "GradNode"]

_tls = threading.local()


def is_grad_enabled() -> bool:
    return getattr(_tls, "grad_enabled", True)


def set_grad_enabled(mode: bool) -> None:
    _tls.grad_enabled = bool(mode)


class _GradCtx:
    def __init__(self, mode: bool):
        self._mode = mode

    def __enter__(self):
        self._prev = is_grad_enabled()
        set_grad_enabled(self._mode)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with _GradCtx(self._mode):
                return fn(*args, **kwargs)
        return wrapper


def no_grad(fn=None):
    """Context manager/decorator disabling tape recording (reference
    fluid/dygraph/base.py:207 no_grad)."""
    ctx = _GradCtx(False)
    return ctx(fn) if fn is not None else ctx


def enable_grad(fn=None):
    ctx = _GradCtx(True)
    return ctx(fn) if fn is not None else ctx


def _is_float(x) -> bool:
    return jnp.issubdtype(jnp.result_type(x), jnp.floating) or \
        jnp.issubdtype(jnp.result_type(x), jnp.complexfloating)


# live-GradNode census behind the eager_max_tape_len safety valve: an
# eager loop that records ops forever without ever running backward
# (the leak shape the flag exists for) fails loudly instead of growing
# host memory until the OOM killer picks a victim. A deque of tokens,
# not an int: append()/pop() are single C calls — atomic under the GIL
# from any thread AND from a GC-triggered __del__ interleaving with an
# in-progress update, where an `n += 1` read-modify-write would lose
# counts (and a lock could self-deadlock when __del__ fires inside the
# locked region of the same thread)
_live_nodes: deque = deque()


def _live_node_count() -> int:
    return len(_live_nodes)


class GradNode:
    """One reverse-graph node: the vjp closure of one executed op plus edges
    to producer nodes / leaf tensors."""

    __slots__ = ("name", "vjp_fn", "in_edges", "out_tensors", "n_outputs",
                 "out_float", "out_shapes", "_counted")

    def __init__(self, name: str, vjp_fn: Callable,
                 in_edges: List[Tuple[Optional["GradNode"], int,
                                      Optional[Tensor]]],
                 out_tensors: List[Tensor]):
        _live_nodes.append(None)
        self._counted = True
        if len(_live_nodes) > flags.flag("eager_max_tape_len"):
            _live_nodes.pop()
            self._counted = False
            raise ResourceExhaustedError(
                f"autograd graph exceeds eager_max_tape_len="
                f"{flags.flag('eager_max_tape_len')} live grad nodes — "
                "an eager loop recording ops without ever calling "
                ".backward() (or running under no_grad()) leaks the "
                "whole graph; wrap inference in no_grad(), call "
                "backward, or raise the flag")
        self.name = name
        self.vjp_fn = vjp_fn
        # Per differentiable input: (producer_node, producer_out_index,
        # leaf_tensor_or_hooked_tensor). producer_node None ⇒ leaf.
        self.in_edges = in_edges
        # weakrefs for hook firing / retain_grad on intermediate outputs
        self.out_tensors = [weakref.ref(t) for t in out_tensors]
        self.n_outputs = len(out_tensors)
        self.out_float = [_is_float(t.data) for t in out_tensors]
        self.out_shapes = [(t.data.shape, t.data.dtype) for t in out_tensors]

    def _uncount(self):
        if self._counted:
            self._counted = False
            try:
                _live_nodes.pop()
            except IndexError:  # pragma: no cover - cannot underflow
                pass            # unless census resets race teardown

    def release(self):
        self.vjp_fn = None
        self.in_edges = []
        self._uncount()

    def __del__(self):
        # a node GC'd without release() (its tensors simply died) must
        # leave the census too, or the valve trips on long well-behaved
        # eager runs
        if getattr(self, "_counted", False):
            self._uncount()


def apply(name: str, pure_fn: Callable, tensor_inputs: Sequence[Tensor],
          n_outputs: Optional[int] = None, **attrs) -> Any:
    """Execute one op eagerly, recording a grad node if needed.

    ``pure_fn`` takes raw jax arrays (same arity as ``tensor_inputs``) plus
    ``attrs`` and returns one array or a tuple of arrays. Inputs that are not
    Tensors are passed through as-is (static arguments). This is the single
    choke-point all eager ops go through — the TraceOp analog.
    """
    from .. import profiler as _prof
    if not _prof._enabled:
        return _apply_impl(name, pure_fn, tensor_inputs, n_outputs, **attrs)
    with _prof.RecordEvent(name):
        return _apply_impl(name, pure_fn, tensor_inputs, n_outputs, **attrs)


def _apply_impl(name: str, pure_fn: Callable,
                tensor_inputs: Sequence[Tensor],
                n_outputs: Optional[int] = None, **attrs) -> Any:
    arrays = [t.data if isinstance(t, Tensor) else t for t in tensor_inputs]

    # AMP auto-cast (reference imperative/amp_auto_cast.cc): white-list ops
    # run in the amp dtype, black-list ops in f32.
    from ..amp import amp_state
    amp = amp_state()
    if amp is not None and amp.enabled:
        import jax.numpy as _jnp
        if name in amp.white:
            arrays = [a.astype(amp.dtype)
                      if hasattr(a, "dtype") and
                      _jnp.issubdtype(a.dtype, _jnp.floating) else a
                      for a in arrays]
        elif name in amp.black:
            arrays = [a.astype(_jnp.float32)
                      if hasattr(a, "dtype") and
                      _jnp.issubdtype(a.dtype, _jnp.floating) and
                      a.dtype != _jnp.float64 else a
                      for a in arrays]

    # Which inputs do we differentiate against?
    diff_idx = []
    if is_grad_enabled():
        for i, t in enumerate(tensor_inputs):
            if isinstance(t, Tensor) and not t.stop_gradient and _is_float(t.data):
                diff_idx.append(i)

    if not diff_idx:
        outs = pure_fn(*arrays, **attrs)
        return _wrap_outputs(name, outs, stop_gradient=True)

    # Close over non-differentiated inputs; vjp only over the float ones.
    def partial_fn(*diff_args):
        full = list(arrays)
        for k, i in enumerate(diff_idx):
            full[i] = diff_args[k]
        return pure_fn(*full, **attrs)

    diff_arrays = [arrays[i] for i in diff_idx]
    outs, vjp_fn = jax.vjp(partial_fn, *diff_arrays)

    out_list, single = _normalize_outputs(outs)
    out_tensors = [Tensor(o, stop_gradient=False) for o in out_list]

    in_edges = []
    for i in diff_idx:
        t = tensor_inputs[i]
        in_edges.append((t._node, t._output_index, t))
    node = GradNode(name, vjp_fn, in_edges, out_tensors)
    for j, ot in enumerate(out_tensors):
        ot._node = node
        ot._output_index = j

    if flags.flag("check_nan_inf"):
        for o in out_list:
            if _is_float(o) and not bool(jnp.all(jnp.isfinite(o))):
                raise PreconditionNotMetError(
                    f"NaN/Inf detected in output of op '{name}'")

    return out_tensors[0] if single else tuple(out_tensors)


def apply_custom_vjp(name: str, fwd_fn: Callable, bwd_fn: Callable,
                     tensor_inputs: Sequence[Tensor], **attrs) -> Any:
    """Execute an op with a *caller-supplied* backward rule.

    The extension point for ops whose cotangents are not plain arrays
    (e.g. embedding's IndexedSlices gradient) or whose backward should not
    be jax.vjp of the forward. ``fwd_fn(*arrays, **attrs)`` returns
    ``(outputs, residuals)``; ``bwd_fn(residuals, cotangents)`` returns one
    gradient per ``tensor_inputs`` entry (None / array / IndexedSlices) —
    the engine keeps only the ones that require grad. This is the analog of
    the reference's custom-operator registration
    (fluid/framework/custom_operator.cc) at the tape level.
    """
    arrays = [t.data if isinstance(t, Tensor) else t for t in tensor_inputs]
    outs, residuals = fwd_fn(*arrays, **attrs)

    diff_idx = []
    if is_grad_enabled():
        for i, t in enumerate(tensor_inputs):
            if isinstance(t, Tensor) and not t.stop_gradient and \
                    _is_float(t.data):
                diff_idx.append(i)
    if not diff_idx:
        return _wrap_outputs(name, outs, stop_gradient=True)

    out_list, single = _normalize_outputs(outs)
    out_tensors = [Tensor(o, stop_gradient=False) for o in out_list]

    def vjp_fn(cotangents):
        all_grads = bwd_fn(residuals, cotangents)
        if not isinstance(all_grads, (tuple, list)):
            all_grads = (all_grads,)
        return tuple(all_grads[i] for i in diff_idx)

    in_edges = []
    for i in diff_idx:
        t = tensor_inputs[i]
        in_edges.append((t._node, t._output_index, t))
    node = GradNode(name, vjp_fn, in_edges, out_tensors)
    for j, ot in enumerate(out_tensors):
        ot._node = node
        ot._output_index = j
    return out_tensors[0] if single else tuple(out_tensors)


def _normalize_outputs(outs):
    if isinstance(outs, (tuple, list)):
        return list(outs), False
    return [outs], True


def _wrap_outputs(name, outs, stop_gradient):
    out_list, single = _normalize_outputs(outs)
    ts = [Tensor(o, stop_gradient=stop_gradient) for o in out_list]
    return ts[0] if single else tuple(ts)


# ---------------------------------------------------------------------------
# Backward execution (BasicEngine analog)
# ---------------------------------------------------------------------------


def _fire_hooks(tensor_ref, g):
    t = tensor_ref() if isinstance(tensor_ref, weakref.ref) else tensor_ref
    if t is None:
        return g
    for entry in t._hooks:
        hook = entry[0]
        if hook is None:
            continue
        res = hook(Tensor(g, stop_gradient=True))
        if res is not None:
            g = res.data if isinstance(res, Tensor) else jnp.asarray(res)
    return g


def _gadd(a, b):
    """Gradient accumulation that understands IndexedSlices fan-in
    (reference GradientAccumulator: SelectedRows+SelectedRows concatenates,
    SelectedRows+dense scatters — gradient_accumulator.cc MergeAdd)."""
    from ..core.indexed_slices import IndexedSlices
    if isinstance(a, IndexedSlices):
        return a + b if isinstance(b, IndexedSlices) else a.add_to_dense(b)
    if isinstance(b, IndexedSlices):
        return b.add_to_dense(a)
    return a + b


def _accumulate(tensor: Tensor, g) -> None:
    if tensor._grad is None:
        tensor._grad = Tensor(g, stop_gradient=True)
    else:
        tensor._grad = Tensor(_gadd(tensor._grad.data, g),
                              stop_gradient=True)


def run_backward(tensors: Sequence[Tensor],
                 grad_tensors: Sequence[Optional[Tensor]],
                 retain_graph: bool = False,
                 collect_for: Optional[Sequence[Tensor]] = None,
                 accumulate_leaves: bool = True,
                 allow_unused: bool = True) -> Optional[List[Optional[Tensor]]]:
    """Reverse pass with dependency counting.

    With ``collect_for`` set, behaves like PartialGradEngine (paddle.grad):
    returns grads for those tensors; ``accumulate_leaves=False`` leaves
    ``.grad`` untouched.
    """
    roots: List[Tuple[GradNode, int, Any]] = []
    leaf_seed: List[Tuple[Tensor, Any]] = []
    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient:
            raise PreconditionNotMetError(
                "backward() on a tensor with stop_gradient=True")
        if g is None:
            if t.size != 1:
                raise InvalidArgumentError(
                    "grad must be provided for non-scalar backward root "
                    f"(shape {t.shape})")
            garr = jnp.ones_like(t.data)
        else:
            garr = g.data if isinstance(g, Tensor) else jnp.asarray(g)
        if t._node is None:
            leaf_seed.append((t, garr))
        else:
            roots.append((t._node, t._output_index, garr))

    # Reachability + dependency counts (BasicEngine::PrepareDeps analog).
    deps: Dict[int, int] = {}
    nodes: Dict[int, GradNode] = {}
    stack = [n for n, _, _ in roots]
    seen = set()
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        nodes[id(n)] = n
        for (pn, pout, _t) in n.in_edges:
            if pn is not None:
                deps[id(pn)] = deps.get(id(pn), 0) + 1
                if id(pn) not in seen:
                    stack.append(pn)

    # Pending output-cotangent buffers per node.
    pending: Dict[int, List[Any]] = {
        nid: [None] * n.n_outputs for nid, n in nodes.items()}
    ready = deque()
    root_ids = set()
    for n, oi, g in roots:
        buf = pending[id(n)]
        buf[oi] = g if buf[oi] is None else _gadd(buf[oi], g)
        root_ids.add(id(n))
    for nid in root_ids:
        if deps.get(nid, 0) == 0:
            ready.append(nid)
    # Nodes only reachable as producers start with their computed dep counts;
    # roots with outstanding consumers wait until those consumers run.

    collect: Dict[int, Any] = {}
    collect_ids = {id(t) for t in (collect_for or [])}

    executed = set()
    while ready:
        nid = ready.popleft()
        if nid in executed:
            continue
        executed.add(nid)
        node = nodes[nid]
        cotangents = []
        for j in range(node.n_outputs):
            g = pending[nid][j]
            if g is None:
                shape, dt = node.out_shapes[j]
                if node.out_float[j]:
                    g = jnp.zeros(shape, dt)
                else:
                    g = np.zeros(shape, jax.dtypes.float0)
            else:
                # fire hooks registered on the *output* tensor of this node
                g = _fire_hooks(node.out_tensors[j], g)
                ot = node.out_tensors[j]()
                if ot is not None and (ot._retain_grad or
                                       flags.flag("retain_grad_for_all")):
                    _accumulate(ot, g)
                if ot is not None and id(ot) in collect_ids:
                    prev = collect.get(id(ot))
                    collect[id(ot)] = g if prev is None else _gadd(prev, g)
            cotangents.append(g)
        outs = cotangents[0] if node.n_outputs == 1 else tuple(cotangents)
        # jax.vjp returned a tuple-cotangent function over the tuple output
        try:
            in_grads = node.vjp_fn(outs)
        except TypeError:
            in_grads = node.vjp_fn(tuple(cotangents))
        if not isinstance(in_grads, (tuple, list)):
            in_grads = (in_grads,)

        for (pn, pout, t), ig in zip(node.in_edges, in_grads):
            if ig is None or (hasattr(ig, "dtype") and
                              ig.dtype == jax.dtypes.float0):
                continue
            if pn is None:
                # Leaf: fire hooks then accumulate into .grad
                ig = _fire_hooks(t, ig)
                if id(t) in collect_ids:
                    prev = collect.get(id(t))
                    collect[id(t)] = ig if prev is None else _gadd(prev, ig)
                if accumulate_leaves:
                    _accumulate(t, ig)
            else:
                pid = id(pn)
                buf = pending[pid]
                buf[pout] = ig if buf[pout] is None else _gadd(buf[pout], ig)
                deps[pid] -= 1
                if deps[pid] == 0:
                    ready.append(pid)
        if not retain_graph:
            node.release()

    # Seeds that were themselves leaves.
    for t, g in leaf_seed:
        g = _fire_hooks(t, g)
        if id(t) in collect_ids:
            prev = collect.get(id(t))
            collect[id(t)] = g if prev is None else _gadd(prev, g)
        if accumulate_leaves:
            _accumulate(t, g)

    if collect_for is not None:
        out = []
        for t in collect_for:
            g = collect.get(id(t))
            if g is None and not allow_unused:
                raise InvalidArgumentError(
                    "One of the differentiated tensors appears unused in the "
                    "graph; pass allow_unused=True to return None for it")
            out.append(None if g is None else Tensor(g, stop_gradient=True))
        return out
    # A full backward (Tensor.backward, not paddle.grad) marks the end of
    # a forward pass — observers (e.g. fluid.layers implicit-parameter
    # pass tracking) hook here.
    for cb in list(_backward_end_callbacks):
        cb()
    return None


_backward_end_callbacks: List[Callable[[], None]] = []


def register_backward_end_callback(fn: Callable[[], None]) -> None:
    """Call ``fn`` after every completed full backward pass."""
    if fn not in _backward_end_callbacks:
        _backward_end_callbacks.append(fn)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False):
    """paddle.grad equivalent (reference fluid/dygraph/base.py:392 →
    PartialGradEngine). ``create_graph`` (double grad) is not yet supported —
    use the functional jax path for higher-order derivatives."""
    if create_graph:
        from ..core.errors import UnimplementedError
        raise UnimplementedError(
            "create_graph=True: use paddle1_tpu.incubate.functional.grad "
            "(jax.grad composition) for higher-order autodiff")
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]
    retain = bool(retain_graph) if retain_graph is not None else False
    return run_backward(outputs, grad_outputs, retain_graph=retain,
                        collect_for=inputs, accumulate_leaves=False,
                        allow_unused=allow_unused)
