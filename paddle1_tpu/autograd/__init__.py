"""Autograd: eager tape engine, paddle.grad, PyLayer, no_grad.

Analog of /root/reference/paddle/fluid/imperative/ (BasicEngine,
PartialGradEngine, hooks) + python/paddle/autograd/.
"""

from .engine import (apply, enable_grad, grad, is_grad_enabled, no_grad,
                     run_backward, set_grad_enabled)
from .py_layer import PyLayer, PyLayerContext

backward = run_backward
