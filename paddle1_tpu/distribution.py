"""Probability distributions (reference python/paddle/distribution.py:
Distribution, Uniform, Normal, Categorical — the v2.0 snapshot's surface).

Sampling draws from the framework PRNG (core.generator), so seeds behave
like the rest of the library; all math is eager-op based and therefore
differentiable and jit-traceable."""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .autograd.engine import apply
from .core.errors import InvalidArgumentError
from .core.generator import next_key
from .core.tensor import Tensor, to_tensor

__all__ = ["Distribution", "Uniform", "Normal", "Categorical"]


def _t(x, dtype="float32"):
    return x if isinstance(x, Tensor) else to_tensor(
        np.asarray(x, np.float32) if not isinstance(x, Tensor) else x,
        dtype=dtype)


class Distribution:
    """Abstract base (reference distribution.py Distribution)."""

    def sample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        from .ops import math_ops
        return math_ops.exp(self.log_prob(value))

    def kl_divergence(self, other):
        raise NotImplementedError


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)

    def sample(self, shape=(), seed=0):
        key = next_key()
        shape = tuple(shape)

        def f(low, high):
            bshape = shape + tuple(np.broadcast_shapes(low.shape, high.shape))
            u = jax.random.uniform(key, bshape, jnp.float32)
            return low + u * (high - low)
        return apply("uniform_sample", f, (self.low, self.high))

    def log_prob(self, value):
        def f(v, low, high):
            inside = (v >= low) & (v < high)
            lp = -jnp.log(high - low)
            return jnp.where(inside, lp, -jnp.inf)
        return apply("uniform_log_prob", f, (_t(value), self.low, self.high))

    def entropy(self):
        def f(low, high):
            return jnp.log(high - low)
        return apply("uniform_entropy", f, (self.low, self.high))


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)

    def sample(self, shape=(), seed=0):
        key = next_key()
        shape = tuple(shape)

        def f(loc, scale):
            bshape = shape + tuple(np.broadcast_shapes(loc.shape,
                                                       scale.shape))
            z = jax.random.normal(key, bshape, jnp.float32)
            return loc + z * scale
        return apply("normal_sample", f, (self.loc, self.scale))

    def log_prob(self, value):
        def f(v, loc, scale):
            var = scale * scale
            return (-((v - loc) ** 2) / (2 * var) - jnp.log(scale) -
                    0.5 * math.log(2 * math.pi))
        return apply("normal_log_prob", f, (_t(value), self.loc, self.scale))

    def entropy(self):
        def f(loc, scale):
            return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(
                scale * jnp.ones_like(loc))
        return apply("normal_entropy", f, (self.loc, self.scale))

    def kl_divergence(self, other: "Normal"):
        if not isinstance(other, Normal):
            raise InvalidArgumentError("kl_divergence expects Normal")

        def f(l0, s0, l1, s1):
            var0, var1 = s0 * s0, s1 * s1
            return (0.5 * (var0 / var1 + (l1 - l0) ** 2 / var1 - 1.0) +
                    jnp.log(s1 / s0))
        return apply("normal_kl", f, (self.loc, self.scale, other.loc,
                                      other.scale))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _t(logits)

    def sample(self, shape=()):
        key = next_key()
        shape = tuple(shape)

        def f(logits):
            return jax.random.categorical(key, logits, axis=-1,
                                          shape=shape + logits.shape[:-1])
        return apply("categorical_sample", f, (self.logits,))

    def log_prob(self, value):
        def f(logits, v):
            logp = jax.nn.log_softmax(logits, axis=-1)
            idx = v.astype(jnp.int32)
            # broadcast category axis against the value batch shape
            logp = jnp.broadcast_to(logp, idx.shape + logp.shape[-1:])
            return jnp.take_along_axis(logp, idx[..., None], axis=-1)[..., 0]
        return apply("categorical_log_prob", f, (self.logits, _t(value,
                                                                 "int64")))

    def entropy(self):
        def f(logits):
            logp = jax.nn.log_softmax(logits, axis=-1)
            p = jnp.exp(logp)
            return -jnp.sum(p * logp, axis=-1)
        return apply("categorical_entropy", f, (self.logits,))

    def kl_divergence(self, other: "Categorical"):
        def f(a, b):
            pa = jax.nn.log_softmax(a, axis=-1)
            pb = jax.nn.log_softmax(b, axis=-1)
            return jnp.sum(jnp.exp(pa) * (pa - pb), axis=-1)
        return apply("categorical_kl", f, (self.logits, other.logits))


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="int32"):  # noqa: A002
    """Sample one class id per row of a probability matrix (reference
    layers/distributions-adjacent sampling_id op; same object the fluid
    spelling maps)."""
    from .fluid.layers_ext import sampling_id as _impl
    return _impl(x, min=min, max=max, seed=seed, dtype=dtype)


def _mvn_diag(loc, scale):
    from .fluid.layers_ext import MultivariateNormalDiag as _M
    return _M(loc, scale)


class MultivariateNormalDiag:
    """Reference fluid/layers/distributions.py:528 — diagonal-covariance
    multivariate normal (entropy + kl_divergence)."""

    def __new__(cls, loc, scale):
        return _mvn_diag(loc, scale)


__all__ += ["MultivariateNormalDiag", "sampling_id"]
