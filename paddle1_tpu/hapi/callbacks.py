"""High-level API callbacks.

Analog of /root/reference/python/paddle/hapi/callbacks.py (Callback base,
ProgBarLogger:296, ModelCheckpoint:528, EarlyStopping, LRScheduler,
VisualDL → here TensorBoard-compatible scalar logging via jax profiler dirs).
"""

from __future__ import annotations

import os
import time
from typing import List, Optional

import numpy as np

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping",
           "LRScheduler", "MetricsCallback", "CallbackList"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks: List[Callback]):
        self.callbacks = callbacks

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def fire(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return fire
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._start = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = " - ".join(
                f"{k}: {_fmt(v)}" for k, v in (logs or {}).items())
            print(f"step {step + 1}/{self.steps or '?'} - {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._start
            items = " - ".join(
                f"{k}: {_fmt(v)}" for k, v in (logs or {}).items())
            print(f"Epoch {epoch + 1} done in {dt:.1f}s - {items}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            items = " - ".join(
                f"{k}: {_fmt(v)}" for k, v in (logs or {}).items())
            print(f"Eval - {items}")


def _fmt(v):
    from ..core.async_loss import LossFuture
    if isinstance(v, LossFuture):
        # formatting IS the materialization point for lazy losses: the
        # device→host readback happens here (once per handle), not in
        # the training loop
        v = v.numpy()
    if isinstance(v, (list, tuple, np.ndarray)):
        return "[" + ", ".join(f"{float(x):.4f}" for x in np.ravel(v)) + "]"
    try:
        return f"{float(v):.4f}"
    except (TypeError, ValueError):
        return str(v)


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "min" if "loss" in monitor else "max"
        self.mode = mode
        self.best = None
        self.wait = 0
        self.stopped_epoch = 0

    def _better(self, cur, best):
        if self.mode == "min":
            return cur < best - self.min_delta
        return cur > best + self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        cur = float(np.ravel(cur)[0]) if not np.isscalar(cur) else float(cur)
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"Early stopping: no improvement in "
                          f"{self.monitor} for {self.patience} evals")


class MetricsCallback(Callback):
    """Publish ``Model.fit``/``evaluate`` progress into the unified
    observability registry (ISSUE 10 satellite), so a hapi run is
    scrapeable from ``/metrics`` exactly like an engine run:

    * ``hapi_steps_total`` / ``hapi_epochs_total`` counters,
    * ``hapi_loss`` gauge (latest train loss) and per-eval-metric
      ``hapi_eval_<name>`` gauges,
    * ``hapi_step_seconds`` histogram and ``hapi_samples_per_s`` gauge
      (throughput from ``batch_size`` × step rate).

    ``log_freq`` bounds the cost: reading a lazy loss materializes it
    (one device→host readback), so the loss gauge updates every
    ``log_freq``-th step — counters and timing are readback-free and
    update every step. Adding the callback is the opt-in; it reports
    into :func:`paddle1_tpu.obs.process_registry` (or a registry you
    pass)."""

    def __init__(self, batch_size: int = 1, log_freq: int = 10,
                 registry=None):
        super().__init__()
        self.batch_size = int(batch_size)
        self.log_freq = max(int(log_freq), 1)
        self._registry = registry
        self._last_t = None

    @property
    def registry(self):
        if self._registry is None:
            from ..obs import process_registry
            self._registry = process_registry()
        return self._registry

    @staticmethod
    def _scalar(v) -> Optional[float]:
        try:
            return float(np.ravel(np.asarray(v))[0])
        except (TypeError, ValueError):
            return None

    def on_epoch_begin(self, epoch, logs=None):
        self.registry.gauge("hapi_epoch").set(epoch)
        self._last_t = time.perf_counter()

    def on_train_batch_end(self, step, logs=None):
        m = self.registry
        m.counter("hapi_steps_total").inc()
        now = time.perf_counter()
        if self._last_t is not None:
            dt = now - self._last_t
            m.histogram("hapi_step_seconds").observe(dt)
            if dt > 0:
                m.gauge("hapi_samples_per_s").set(self.batch_size / dt)
        self._last_t = now
        if step % self.log_freq == 0:
            losses = (logs or {}).get("loss")
            if losses is not None:
                vals = losses if isinstance(losses, (list, tuple)) \
                    else [losses]
                v = self._scalar(vals[0])  # materializes a lazy loss
                if v is not None:
                    m.gauge("hapi_loss").set(v)

    def on_epoch_end(self, epoch, logs=None):
        self.registry.counter("hapi_epochs_total").inc()

    def on_eval_end(self, logs=None):
        m = self.registry
        for k, v in (logs or {}).items():
            v = self._scalar(v)
            if v is not None:
                m.gauge(f"hapi_eval_{_metric_slug(k)}").set(v)


def _metric_slug(name: str) -> str:
    """Metric-name-safe slug of a user metric key (the lint contract:
    snake_case, nothing the exposition format chokes on)."""
    out = []
    for ch in str(name).lower():
        out.append(ch if ch.isalnum() else "_")
    slug = "".join(out).strip("_") or "metric"
    return slug if slug[0].isalpha() else "m_" + slug


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        from ..optimizer.lr import LRScheduler as Sched
        if opt is not None and isinstance(opt._learning_rate, Sched):
            return opt._learning_rate
        return None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if s and self.by_step:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if s and self.by_epoch:
            s.step()
