"""High-level API callbacks.

Analog of /root/reference/python/paddle/hapi/callbacks.py (Callback base,
ProgBarLogger:296, ModelCheckpoint:528, EarlyStopping, LRScheduler,
VisualDL → here TensorBoard-compatible scalar logging via jax profiler dirs).
"""

from __future__ import annotations

import os
import time
from typing import List, Optional

import numpy as np

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping",
           "LRScheduler", "CallbackList"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks: List[Callback]):
        self.callbacks = callbacks

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def fire(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return fire
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._start = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = " - ".join(
                f"{k}: {_fmt(v)}" for k, v in (logs or {}).items())
            print(f"step {step + 1}/{self.steps or '?'} - {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._start
            items = " - ".join(
                f"{k}: {_fmt(v)}" for k, v in (logs or {}).items())
            print(f"Epoch {epoch + 1} done in {dt:.1f}s - {items}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            items = " - ".join(
                f"{k}: {_fmt(v)}" for k, v in (logs or {}).items())
            print(f"Eval - {items}")


def _fmt(v):
    from ..core.async_loss import LossFuture
    if isinstance(v, LossFuture):
        # formatting IS the materialization point for lazy losses: the
        # device→host readback happens here (once per handle), not in
        # the training loop
        v = v.numpy()
    if isinstance(v, (list, tuple, np.ndarray)):
        return "[" + ", ".join(f"{float(x):.4f}" for x in np.ravel(v)) + "]"
    try:
        return f"{float(v):.4f}"
    except (TypeError, ValueError):
        return str(v)


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "min" if "loss" in monitor else "max"
        self.mode = mode
        self.best = None
        self.wait = 0
        self.stopped_epoch = 0

    def _better(self, cur, best):
        if self.mode == "min":
            return cur < best - self.min_delta
        return cur > best + self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        cur = float(np.ravel(cur)[0]) if not np.isscalar(cur) else float(cur)
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"Early stopping: no improvement in "
                          f"{self.monitor} for {self.patience} evals")


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        from ..optimizer.lr import LRScheduler as Sched
        if opt is not None and isinstance(opt._learning_rate, Sched):
            return opt._learning_rate
        return None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if s and self.by_step:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if s and self.by_epoch:
            s.step()
