"""paddle1_tpu.hapi — high-level Model API (reference python/paddle/hapi)."""

from . import callbacks
from .model import Model
from .model_summary import flops, summary
