"""Model summary + flops (reference python/paddle/hapi/model_summary.py,
dynamic_flops.py)."""

from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["summary", "flops"]


def summary(net, input_size=None, dtypes=None, input=None):
    """Parameter-count table. Returns {'total_params': n,
    'trainable_params': n} like the reference."""
    total = 0
    trainable = 0
    rows = []
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total += n
        if not p.stop_gradient:
            trainable += n
        rows.append((name, tuple(p.shape), n))
    width = max([len(r[0]) for r in rows], default=20) + 2
    print(f"{'Layer (param)':<{width}}{'Shape':<24}{'Param #':>12}")
    print("-" * (width + 36))
    for name, shape, n in rows:
        print(f"{name:<{width}}{str(shape):<24}{n:>12,}")
    print("-" * (width + 36))
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    return {"total_params": total, "trainable_params": trainable}


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Rough analytic FLOPs: 2 * params touched per matmul/conv output.
    Uses jax's cost analysis on the jitted forward when available — exact
    for the compiled graph."""
    import jax
    import jax.numpy as jnp
    from ..incubate.functional import functional_call
    params = net.functional_state()
    x = jnp.zeros(input_size, jnp.float32)
    try:
        lowered = jax.jit(
            lambda p, x: functional_call(net, p, x)).lower(params, x)
        cost = lowered.compile().cost_analysis()
        if cost and "flops" in cost:
            total = int(cost["flops"])
            if print_detail:
                print(f"Total FLOPs (XLA cost analysis): {total:,}")
            return total
    except Exception:
        pass
    total = sum(int(np.prod(p.shape)) for p in net.parameters()) * 2
    return total
