"""Model summary + flops (reference python/paddle/hapi/model_summary.py,
dynamic_flops.py).

ISSUE 13 satellite: ``summary`` grew the reference's FLOPs column
(the ``paddle.summary`` parity gap noted in MIGRATING) — per-parameter
analytic estimates in the table, and an EXACT total from
``obs.costmodel.forward_cost`` (XLA cost analysis of the compiled
eval forward) when an ``input_size`` is given. When cost analysis is
unavailable the total falls back to the labeled tree-size heuristic
and the printout says so — a guess must never read as a measurement.
"""

from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["summary", "flops"]


def _row_flops(shape, batch: int):
    """Per-parameter analytic FLOPs estimate for the table column:
    2 * elements * batch for matrix-like params (one MAC touching each
    weight per row — a dense floor), '-' for biases/scalars where the
    estimate would be noise."""
    if len(shape) >= 2:
        return 2 * int(np.prod(shape)) * batch
    return None


def summary(net, input_size=None, dtypes=None, input=None):
    """Parameter-count table, with a FLOPs column when ``input_size``
    (or an example ``input``) pins the forward shape. Returns
    {'total_params', 'trainable_params'} like the reference, plus
    {'total_flops', 'flops_source'} when FLOPs were computed
    ('xla_cost_analysis' = exact for the compiled graph,
    'tree_size_heuristic' = the labeled fallback)."""
    if input_size is None and input is not None:
        input_size = tuple(np.shape(
            input.data if isinstance(input, Tensor) else input))
    batch = int(input_size[0]) if input_size else 1

    total = 0
    trainable = 0
    rows = []
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total += n
        if not p.stop_gradient:
            trainable += n
        rows.append((name, tuple(p.shape), n,
                     _row_flops(p.shape, batch) if input_size else None))
    width = max([len(r[0]) for r in rows], default=20) + 2
    with_flops = input_size is not None
    header = f"{'Layer (param)':<{width}}{'Shape':<24}{'Param #':>12}"
    if with_flops:
        header += f"{'FLOPs (est.)':>16}"
    print(header)
    print("-" * (width + 36 + (16 if with_flops else 0)))
    for name, shape, n, fl in rows:
        line = f"{name:<{width}}{str(shape):<24}{n:>12,}"
        if with_flops:
            line += f"{fl:>16,}" if fl is not None else f"{'-':>16}"
        print(line)
    print("-" * (width + 36 + (16 if with_flops else 0)))
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    out = {"total_params": total, "trainable_params": trainable}
    if with_flops:
        from ..obs import costmodel
        cost = costmodel.forward_cost(
            net, input_size,
            dtype=(dtypes[0] if dtypes else "float32"))
        out["total_flops"] = int(cost.flops)
        out["flops_source"] = cost.source
        if cost.exact:
            print(f"Total FLOPs (XLA cost analysis, forward): "
                  f"{int(cost.flops):,}")
        else:
            print(f"Total FLOPs (ESTIMATE — XLA cost analysis "
                  f"unavailable on this backend; tree-size heuristic "
                  f"2*params*batch): {int(cost.flops):,}")
    return out


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Analytic FLOPs of one forward at ``input_size`` — exact via
    ``obs.costmodel.forward_cost`` (XLA cost analysis of the compiled
    graph) when available, labeled tree-size heuristic otherwise."""
    from ..obs import costmodel
    cost = costmodel.forward_cost(net, input_size)
    if print_detail:
        label = ("XLA cost analysis" if cost.exact
                 else "tree-size heuristic — cost analysis unavailable")
        print(f"Total FLOPs ({label}): {int(cost.flops):,}")
    return int(cost.flops)
