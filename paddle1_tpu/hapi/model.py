"""Keras-like high-level Model.

Analog of /root/reference/python/paddle/hapi/model.py (Model:876, fit:1519,
evaluate/predict/save/load:1160; the dual static+dygraph adapters at
:294/:697 collapse into one eager path — jit compilation is applied inside
train_batch via to_static when beneficial).
"""

from __future__ import annotations

import collections
import os
from typing import List, Optional, Sequence

import numpy as np

from ..core.async_loss import LossFuture
from ..core.tensor import Tensor, to_tensor
from ..core.errors import InvalidArgumentError
from ..io import DataLoader, Dataset
from ..metric import Metric
from .callbacks import Callback, CallbackList, ProgBarLogger

__all__ = ["Model"]


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self.stop_training = False

    # -- configuration ------------------------------------------------------

    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _as_list(metrics)
        return self

    # -- per-batch ops ------------------------------------------------------

    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = _as_list(inputs)
        labels = _as_list(labels)
        outputs = self.network(*[_to_tensor(i) for i in inputs])
        losses = self._compute_loss(outputs, labels)
        total = losses[0] if len(losses) == 1 else _sum_losses(losses)
        total.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = self._update_metrics(outputs, labels)
        # Lazy handles, not floats: a blocking float(l.item()) here costs
        # a device→host readback EVERY batch (~70 ms through the axon
        # tunnel — bench.py honesty contract), serializing the whole loop
        # on the host. The future reads back only when someone formats or
        # floats it (ProgBarLogger, or an explicit .item()).
        loss_vals = [LossFuture(l) for l in losses]
        if metrics:
            return loss_vals, metrics
        return loss_vals

    def eval_batch(self, inputs, labels=None):
        from ..autograd import engine
        self.network.eval()
        with engine.no_grad():
            inputs = _as_list(inputs)
            labels = _as_list(labels)
            outputs = self.network(*[_to_tensor(i) for i in inputs])
            losses = self._compute_loss(outputs, labels) if self._loss else []
            metrics = self._update_metrics(outputs, labels)
        loss_vals = [float(l.item()) for l in losses]
        if metrics:
            return loss_vals, metrics
        return loss_vals

    def predict_batch(self, inputs):
        from ..autograd import engine
        self.network.eval()
        with engine.no_grad():
            inputs = _as_list(inputs)
            out = self.network(*[_to_tensor(i) for i in inputs])
        return [o.numpy() for o in _as_list(out)]

    def _compute_loss(self, outputs, labels):
        if self._loss is None:
            return []
        outs = _as_list(outputs)
        loss = self._loss(*(outs + labels))
        return _as_list(loss)

    def _update_metrics(self, outputs, labels):
        res = {}
        outs = _as_list(outputs)
        for m in self._metrics:
            computed = m.compute(*(outs + labels))
            r = m.update(*(computed if isinstance(computed, (list, tuple))
                           else [computed]))
            res[m.name()] = r
        return res

    # -- loops --------------------------------------------------------------

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None, resume=False):
        """Train for ``epochs`` epochs.

        Fault-tolerance knobs (the reference incubate/auto_checkpoint
        train_epoch_range role at the hapi level; engine-scale runs
        should use :class:`paddle1_tpu.distributed.ResilientTrainer`):
        ``save_dir`` + ``save_freq`` checkpoint network+optimizer every
        N epochs; ``resume=True`` picks the largest epoch checkpoint
        already under ``save_dir`` (non-numeric/partial entries are
        skipped), loads it, and continues from the NEXT epoch. When the
        train loader is a checkpointable :class:`~paddle1_tpu.io.
        DataLoader`, each epoch checkpoint also writes an
        ``<epoch>.pdloader`` sidecar (loader state + RNG stream) and
        ``resume=True`` restores it, so the resumed run's epoch
        ordering continues exactly where the interrupted run's would
        have — otherwise a one-time warning notes that ordering
        restarts.
        """
        start_epoch = 0
        latest = None
        if resume:
            if not save_dir:
                raise InvalidArgumentError(
                    "fit(resume=True) needs save_dir (the checkpoint "
                    "directory to resume from)")
            latest = _latest_saved_epoch(save_dir)
            if latest is not None:
                self.load(os.path.join(save_dir, str(latest)))
                start_epoch = latest + 1
        train_loader = self._to_loader(train_data, batch_size, shuffle,
                                       drop_last, num_workers)
        if latest is not None:
            _restore_loader_state(save_dir, latest, train_loader)
        eval_loader = self._to_loader(eval_data, batch_size, False, False,
                                      num_workers) if eval_data is not None \
            else None
        cbks = CallbackList((_as_list(callbacks) or []) +
                            [ProgBarLogger(log_freq, verbose)])
        cbks.set_model(self)
        try:
            steps = len(train_loader)
        except (RuntimeError, TypeError):
            steps = None
        cbks.set_params({"epochs": epochs, "steps": steps,
                         "verbose": verbose})
        self.stop_training = False
        cbks.on_train_begin()
        it = 0
        if start_epoch >= epochs:
            cbks.on_train_end()
            return
        # Bounded dispatch run-ahead: keep at most `window` batches of
        # un-synchronized loss futures outstanding, then block (device
        # sync, NOT a readback) on the oldest — dispatch runs ahead of
        # the device without unbounded live-buffer growth.
        window: collections.deque = collections.deque()
        window_size = 2
        for epoch in range(start_epoch, epochs):
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, batch in enumerate(train_loader):
                cbks.on_train_batch_begin(step)
                ins, labs = _split_batch(batch)
                update = (step + 1) % accumulate_grad_batches == 0
                res = self.train_batch(ins, labs, update=update)
                logs = _logs_from(res, self._metrics)
                for lv in logs.get("loss", []):
                    if isinstance(lv, LossFuture):
                        window.append(lv)
                while len(window) > window_size:
                    window.popleft().block()
                cbks.on_train_batch_end(step, logs)
                it += 1
                if (num_iters is not None and it >= num_iters) or \
                        self.stop_training:
                    break
            cbks.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_loader, batch_size=batch_size,
                              verbose=verbose, callbacks=callbacks)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(os.path.join(save_dir, str(epoch)))
                _save_loader_state(save_dir, epoch, train_loader)
            if self.stop_training or (num_iters is not None and
                                      it >= num_iters):
                break
        cbks.on_train_end()

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        loader = self._to_loader(eval_data, batch_size, False, False,
                                 num_workers)
        cbks = CallbackList((_as_list(callbacks) or []) +
                            [ProgBarLogger(log_freq, verbose)])
        cbks.set_model(self)
        cbks.set_params({})
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin()
        logs = {}
        for step, batch in enumerate(loader):
            ins, labs = _split_batch(batch)
            res = self.eval_batch(ins, labs)
            logs = _logs_from(res, self._metrics)
        final = {}
        if self._loss is not None and "loss" in logs:
            final["loss"] = logs["loss"]
        for m in self._metrics:
            final[m.name()] = m.accumulate()
        cbks.on_eval_end(final)
        return final

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=1):
        loader = self._to_loader(test_data, batch_size, False, False,
                                 num_workers)
        outputs = []
        for batch in loader:
            ins, _ = _split_batch(batch, has_labels=False)
            outputs.append(self.predict_batch(ins))
        if stack_outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs])
                    for i in range(n_out)]
        return outputs

    # -- persistence --------------------------------------------------------

    def save(self, path, training=True):
        from ..framework.io import save as fsave
        fsave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fsave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as fload
        state = fload(path + ".pdparams")
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(opt_path):
            self._optimizer.set_state_dict(fload(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .model_summary import summary
        return summary(self.network, input_size, dtypes=dtype)

    def _to_loader(self, data, batch_size, shuffle, drop_last, num_workers):
        if data is None:
            return None
        if isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              drop_last=drop_last, num_workers=num_workers)
        return data  # assume iterable of batches


def _loader_sidecar(save_dir, epoch):
    return os.path.join(save_dir, f"{epoch}.pdloader")


def _save_loader_state(save_dir, epoch, loader):
    """Write the ``<epoch>.pdloader`` sidecar: loader position + the
    global RNG stream (the next epoch's shuffle seed is drawn from it,
    so ordering parity needs both). Checkpointing must never fail the
    epoch that just trained — problems degrade to a warning."""
    import json
    import warnings
    from ..io import DataLoader
    if not isinstance(loader, DataLoader) or not loader.checkpointable():
        return
    from ..core.generator import get_rng_state
    try:
        doc = {"version": 1, "loader": loader.state_dict(),
               "rng": get_rng_state()}
        tmp = _loader_sidecar(save_dir, epoch) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, _loader_sidecar(save_dir, epoch))
    except Exception as e:
        warnings.warn(f"loader state sidecar not written ({e}); "
                      "resume will restart epoch ordering")


_FALLBACK_WARNED = set()


def _restore_loader_state(save_dir, epoch, loader):
    """Apply the ``<epoch>.pdloader`` sidecar to a resumed fit's
    loader; warns ONCE per save_dir when it must fall back (missing
    sidecar / non-checkpointable loader) so the user knows the resumed
    run's data order restarts instead of continuing."""
    import json
    import warnings
    from ..io import DataLoader

    def fallback(why):
        if save_dir not in _FALLBACK_WARNED:
            _FALLBACK_WARNED.add(save_dir)
            warnings.warn(
                f"fit(resume=True): loader state not restored ({why}); "
                "epoch ordering restarts from scratch — pass a "
                "checkpointable io.DataLoader (built-in samplers) to "
                "resume the data stream exactly")

    path = _loader_sidecar(save_dir, epoch)
    if not isinstance(loader, DataLoader) or not loader.checkpointable():
        if os.path.exists(path):
            fallback("train loader is not checkpointable")
        return
    if not os.path.exists(path):
        fallback(f"no {os.path.basename(path)} sidecar — checkpoint "
                 "predates loader-state support")
        return
    try:
        with open(path) as f:
            doc = json.load(f)
        # loader state FIRST: it validates eagerly, so a corrupt
        # sidecar fails before the global RNG is touched — the
        # fallback's "ordering restarts from scratch" promise must
        # describe a process whose RNG stream really is untouched
        loader.set_state_dict(doc["loader"])
        from ..core.generator import set_rng_state
        if "rng" in doc:
            set_rng_state(doc["rng"])
    except Exception as e:
        fallback(f"unreadable sidecar: {e}")


def _latest_saved_epoch(save_dir):
    """Largest N with ``<save_dir>/<N>.pdparams`` present, or None.
    Non-numeric and partial entries (a ``.pdparams`` name that doesn't
    parse, or files from other tooling) are skipped, mirroring the
    hardened ``distributed.checkpoint.latest_step``."""
    import re
    if not os.path.isdir(save_dir):
        return None
    best = None
    for name in os.listdir(save_dir):
        m = re.fullmatch(r"(\d+)\.pdparams", name)
        if m is not None:
            n = int(m.group(1))
            best = n if best is None else max(best, n)
    return best


def _to_tensor(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _split_batch(batch, has_labels=True):
    if isinstance(batch, (list, tuple)):
        if has_labels and len(batch) >= 2:
            return list(batch[:-1]), [batch[-1]]
        return list(batch), []
    return [batch], []


def _sum_losses(losses):
    total = losses[0]
    for l in losses[1:]:
        total = total + l
    return total


def _logs_from(res, metrics):
    logs = {}
    if isinstance(res, tuple):
        loss_vals, m = res
        logs["loss"] = loss_vals
        logs.update(m)
    else:
        logs["loss"] = res
    return logs
