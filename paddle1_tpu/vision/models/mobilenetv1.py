"""Reference submodule spelling (vision/models/mobilenetv1.py): the
implementation lives in mobilenet.py."""
from .mobilenet import MobileNetV1, mobilenet_v1  # noqa: F401

__all__ = ["MobileNetV1", "mobilenet_v1"]
