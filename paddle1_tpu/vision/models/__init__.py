"""Vision models (reference python/paddle/vision/models/)."""

from .lenet import LeNet
