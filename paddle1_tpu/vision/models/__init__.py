"""Vision models (reference python/paddle/vision/models/)."""

from .lenet import LeNet
from .mobilenet import MobileNetV1, MobileNetV2, mobilenet_v1, mobilenet_v2
from .resnet import (ResNet, resnet18, resnet34, resnet50, resnet101,
                     resnet152, wide_resnet50_2, wide_resnet101_2)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19
from .yolo import DarkNet53, YOLOv3, yolov3, yolov3_loss
# reference submodule spellings (vision/models/__init__ exposes the
# implementation modules by name too)
from . import lenet, mobilenet, mobilenetv1, mobilenetv2, resnet, vgg

__all__ = ["LeNet", "ResNet", "resnet18", "resnet34", "resnet50",
           "resnet101", "resnet152", "wide_resnet50_2", "wide_resnet101_2",
           "VGG", "vgg11", "vgg13", "vgg16", "vgg19", "MobileNetV1",
           "MobileNetV2", "mobilenet_v1", "mobilenet_v2", "DarkNet53",
           "YOLOv3", "yolov3", "yolov3_loss"]
