"""MobileNetV1/V2 (reference python/paddle/vision/models/
{mobilenetv1,mobilenetv2}.py). Depthwise convs (groups == channels) lower to
XLA grouped convolutions."""

from __future__ import annotations

from ...nn.layer_base import Layer
from ...nn.layer_common import Dropout, Linear
from ...nn.layer_conv_pool import AdaptiveAvgPool2D, Conv2D
from ...nn.layer_norm_act import BatchNorm2D, ReLU, ReLU6, Sequential

__all__ = ["MobileNetV1", "MobileNetV2", "mobilenet_v1", "mobilenet_v2"]


class ConvBNLayer(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, groups=1, act=ReLU):
        super().__init__()
        self.conv = Conv2D(in_channels, out_channels, kernel_size,
                           stride=stride, padding=padding, groups=groups,
                           bias_attr=False)
        self.bn = BatchNorm2D(out_channels)
        self.act = act() if act is not None else None

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act is not None else x


class DepthwiseSeparable(Layer):
    def __init__(self, in_channels, out_channels1, out_channels2, stride,
                 scale):
        super().__init__()
        c1 = int(out_channels1 * scale)
        c2 = int(out_channels2 * scale)
        self.depthwise = ConvBNLayer(in_channels, c1, 3, stride=stride,
                                     padding=1, groups=in_channels)
        self.pointwise = ConvBNLayer(c1, c2, 1)

    def forward(self, x):
        return self.pointwise(self.depthwise(x))


class MobileNetV1(Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool
        s = lambda c: int(c * scale)
        self.conv1 = ConvBNLayer(3, s(32), 3, stride=2, padding=1)
        cfg = [  # in, out1, out2, stride
            (s(32), 32, 64, 1), (s(64), 64, 128, 2), (s(128), 128, 128, 1),
            (s(128), 128, 256, 2), (s(256), 256, 256, 1),
            (s(256), 256, 512, 2)] + [(s(512), 512, 512, 1)] * 5 + [
            (s(512), 512, 1024, 2), (s(1024), 1024, 1024, 1)]
        self.blocks = Sequential(*[
            DepthwiseSeparable(i, o1, o2, st, scale) for i, o1, o2, st in cfg])
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = Linear(s(1024), num_classes)

    def forward(self, x):
        x = self.blocks(self.conv1(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...ops import manip_ops
            x = self.fc(manip_ops.flatten(x, 1))
        return x


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class InvertedResidual(Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        self.stride = stride
        hidden_dim = int(round(inp * expand_ratio))
        self.use_res_connect = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNLayer(inp, hidden_dim, 1, act=ReLU6))
        layers += [
            ConvBNLayer(hidden_dim, hidden_dim, 3, stride=stride, padding=1,
                        groups=hidden_dim, act=ReLU6),
            ConvBNLayer(hidden_dim, oup, 1, act=None),
        ]
        self.conv = Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res_connect else out


class MobileNetV2(Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        input_channel = _make_divisible(32 * scale)
        cfg = [  # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        features = [ConvBNLayer(3, input_channel, 3, stride=2, padding=1,
                                act=ReLU6)]
        for t, c, n, s in cfg:
            output_channel = _make_divisible(c * scale)
            for i in range(n):
                features.append(InvertedResidual(
                    input_channel, output_channel, s if i == 0 else 1, t))
                input_channel = output_channel
        self.last_channel = _make_divisible(1280 * max(1.0, scale))
        features.append(ConvBNLayer(input_channel, self.last_channel, 1,
                                    act=ReLU6))
        self.features = Sequential(*features)
        if with_pool:
            self.pool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = Sequential(Dropout(0.2),
                                         Linear(self.last_channel,
                                                num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...ops import manip_ops
            x = self.classifier(manip_ops.flatten(x, 1))
        return x


def _check_pretrained(pretrained):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled (no network egress); "
            "load a checkpoint explicitly with paddle.load + set_state_dict")


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    _check_pretrained(pretrained)
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    _check_pretrained(pretrained)
    return MobileNetV2(scale=scale, **kwargs)
