"""Reference submodule spelling (vision/models/mobilenetv2.py): the
implementation lives in mobilenet.py."""
from .mobilenet import MobileNetV2, mobilenet_v2  # noqa: F401

__all__ = ["MobileNetV2", "mobilenet_v2"]
