"""YOLOv3 detection model (the PaddleDetection-era baseline the reference
ships ops for: yolo_box_op, yolov3_loss_op, multiclass_nms_op).

DarkNet-53 backbone + FPN-style neck + per-scale heads; postprocess =
vision.ops.yolo_box + multiclass_nms. Anchor config matches the standard
COCO setup. Training uses :func:`yolov3_loss` (dense per-cell targets —
the reference's yolov3_loss_op semantics, vectorized)."""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from ...nn.layer_base import Layer
from ...nn.layer_conv_pool import Conv2D
from ...nn.layer_norm_act import BatchNorm2D, LeakyReLU, Sequential

__all__ = ["DarkNet53", "YOLOv3", "yolov3", "yolov3_loss"]

_ANCHORS = [10, 13, 16, 30, 33, 23, 30, 61, 62, 45, 59, 119, 116, 90,
            156, 198, 373, 326]
_MASKS = [[6, 7, 8], [3, 4, 5], [0, 1, 2]]


class ConvBNLeaky(Layer):
    def __init__(self, cin, cout, k, stride=1):
        super().__init__()
        self.conv = Conv2D(cin, cout, k, stride=stride, padding=k // 2,
                           bias_attr=False)
        self.bn = BatchNorm2D(cout)
        self.act = LeakyReLU(0.1)

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))


class DarkBlock(Layer):
    def __init__(self, ch):
        super().__init__()
        self.conv1 = ConvBNLeaky(ch, ch // 2, 1)
        self.conv2 = ConvBNLeaky(ch // 2, ch, 3)

    def forward(self, x):
        return x + self.conv2(self.conv1(x))


class DarkNet53(Layer):
    """Backbone emitting C3/C4/C5 (reference-era darknet.py)."""

    def __init__(self, depths=(1, 2, 8, 8, 4)):
        super().__init__()
        self.stem = ConvBNLeaky(3, 32, 3)
        chans = [64, 128, 256, 512, 1024]
        stages = []
        cin = 32
        for ch, n in zip(chans, depths):
            blocks = [ConvBNLeaky(cin, ch, 3, stride=2)]
            blocks += [DarkBlock(ch) for _ in range(n)]
            stages.append(Sequential(*blocks))
            cin = ch
        self.stage1, self.stage2, self.stage3, self.stage4, self.stage5 = \
            stages

    def forward(self, x):
        x = self.stem(x)
        x = self.stage1(x)
        x = self.stage2(x)
        c3 = self.stage3(x)
        c4 = self.stage4(c3)
        c5 = self.stage5(c4)
        return c3, c4, c5


class YoloDetBlock(Layer):
    def __init__(self, cin, ch):
        super().__init__()
        self.body = Sequential(
            ConvBNLeaky(cin, ch, 1), ConvBNLeaky(ch, ch * 2, 3),
            ConvBNLeaky(ch * 2, ch, 1), ConvBNLeaky(ch, ch * 2, 3),
            ConvBNLeaky(ch * 2, ch, 1))
        self.tip = ConvBNLeaky(ch, ch * 2, 3)

    def forward(self, x):
        route = self.body(x)
        return route, self.tip(route)


class YOLOv3(Layer):
    def __init__(self, num_classes=80, anchors=None, anchor_masks=None):
        super().__init__()
        self.num_classes = num_classes
        self.anchors = anchors or _ANCHORS
        self.anchor_masks = anchor_masks or _MASKS
        self.backbone = DarkNet53()
        out_ch = 3 * (5 + num_classes)
        self.block5 = YoloDetBlock(1024, 512)
        self.block4 = YoloDetBlock(512 + 256, 256)
        self.block3 = YoloDetBlock(256 + 128, 128)
        self.route5 = ConvBNLeaky(512, 256, 1)
        self.route4 = ConvBNLeaky(256, 128, 1)
        self.head5 = Conv2D(1024, out_ch, 1)
        self.head4 = Conv2D(512, out_ch, 1)
        self.head3 = Conv2D(256, out_ch, 1)

    def forward(self, x):
        from ...nn import functional as F
        c3, c4, c5 = self.backbone(x)
        r5, t5 = self.block5(c5)
        p5 = self.head5(t5)
        u5 = F.interpolate(self.route5(r5), scale_factor=2, mode="nearest")
        from ...ops import manip_ops
        r4, t4 = self.block4(manip_ops.concat([u5, c4], axis=1))
        p4 = self.head4(t4)
        u4 = F.interpolate(self.route4(r4), scale_factor=2, mode="nearest")
        r3, t3 = self.block3(manip_ops.concat([u4, c3], axis=1))
        p3 = self.head3(t3)
        return [p5, p4, p3]     # strides 32, 16, 8

    def postprocess(self, outputs, img_size, conf_thresh=0.01,
                    nms_thresh=0.45, keep_top_k=100):
        """Decode + NMS one batch (host-side; the compiled path stops at
        the head outputs, matching the reference's deploy split).

        Pinned to the host CPU backend when one coexists with an
        accelerator: the decode+NMS loop is hundreds of small eager
        ops, and through the axon relay each device dispatch pays a
        round trip (r5 measured the same batch at 58.6 s on-device vs
        sub-second on host)."""
        import jax as _jax
        try:
            _cpu = _jax.devices("cpu")[0]
        except RuntimeError:
            _cpu = None
        if _cpu is not None and _jax.default_backend() != "cpu":
            from ...core.tensor import Tensor as _T

            def _host(t):
                a = np.asarray(t.numpy() if isinstance(t, _T) else t)
                return _T(_jax.device_put(a, _cpu))
            with _jax.default_device(_cpu):
                return self._postprocess_impl(
                    [_host(o) for o in outputs], _host(img_size),
                    conf_thresh, nms_thresh, keep_top_k)
        return self._postprocess_impl(outputs, img_size, conf_thresh,
                                      nms_thresh, keep_top_k)

    def _postprocess_impl(self, outputs, img_size, conf_thresh,
                          nms_thresh, keep_top_k):
        from .. import ops as V
        from ...ops import manip_ops
        all_boxes, all_scores = [], []
        for out, mask, stride in zip(outputs, self.anchor_masks,
                                     (32, 16, 8)):
            sub_anchors = []
            for m in mask:
                sub_anchors += self.anchors[2 * m:2 * m + 2]
            b, s = V.yolo_box(out, img_size, sub_anchors, self.num_classes,
                              conf_thresh, stride)
            all_boxes.append(b)
            all_scores.append(s)
        boxes = manip_ops.concat(all_boxes, axis=1)
        scores = manip_ops.concat(all_scores, axis=1)
        results = []
        for bi in range(boxes.shape[0]):
            res = V.multiclass_nms(
                boxes[bi], manip_ops.transpose(scores[bi], [1, 0]),
                score_threshold=conf_thresh, nms_threshold=nms_thresh,
                keep_top_k=keep_top_k)
            results.append(res)
        return results


def yolov3(pretrained=False, num_classes=80, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled (no network egress)")
    return YOLOv3(num_classes=num_classes, **kwargs)


def yolov3_loss(outputs, gt_boxes, gt_labels, anchors=None,
                anchor_masks=None, num_classes=80, ignore_thresh=0.7,
                downsample_ratios=(32, 16, 8), gt_scores=None,
                use_label_smooth=False, scale_x_y=1.0):
    """YOLOv3 training loss (reference yolov3_loss_op), vectorized.

    gt_boxes: [B, G, 4] cxcywh normalized to [0,1]; gt_labels: [B, G]
    int (−1 pads). ``gt_scores`` [B, G] weights each gt's loss terms
    (mixup); ``use_label_smooth`` applies the op's
    min(1/C, 1/40) positive/negative smoothing; ``scale_x_y`` decodes
    x = s·sigmoid(tx) − (s−1)/2 (yolov3_loss_op.h:287-291,390).
    Returns scalar loss summing obj/cls/box terms.
    """
    import jax.numpy as jnp

    from ...autograd.engine import apply
    from ...core.tensor import Tensor
    anchors = np.asarray(anchors or _ANCHORS, np.float32).reshape(-1, 2)
    anchor_masks = anchor_masks or _MASKS
    if use_label_smooth:
        sw = min(1.0 / num_classes, 1.0 / 40)
        label_pos, label_neg = 1.0 - sw, sw
    else:
        label_pos, label_neg = 1.0, 0.0
    sxy = float(scale_x_y)

    def one_level(pred, gtb, gtl, gts, mask, ds):
        na = len(mask)
        b, _, h, w = pred.shape
        pred = pred.reshape(b, na, 5 + num_classes, h, w)
        tx, ty = pred[:, :, 0], pred[:, :, 1]
        tw, th = pred[:, :, 2], pred[:, :, 3]
        tobj = pred[:, :, 4]
        tcls = pred[:, :, 5:]
        sub = anchors[mask]                       # [na, 2]

        # build dense targets: for each gt, which cell/anchor owns it
        gx = gtb[:, :, 0] * w                     # [B, G]
        gy = gtb[:, :, 1] * h
        gw = gtb[:, :, 2]
        gh = gtb[:, :, 3]
        valid = (gtl >= 0) & (gw > 0)
        ci = jnp.clip(gx.astype(jnp.int32), 0, w - 1)
        cj = jnp.clip(gy.astype(jnp.int32), 0, h - 1)
        # best anchor per gt by wh-IoU against ALL anchors, then keep
        # those assigned to this level's mask
        gwh = jnp.stack([gw, gh], -1)[..., None, :] * jnp.asarray(
            [w * ds, h * ds], jnp.float32)        # pixels [B,G,1,2]
        awh = jnp.asarray(anchors, jnp.float32)[None, None]  # [1,1,A,2]
        inter = jnp.minimum(gwh, awh).prod(-1)
        union = gwh.prod(-1) + awh.prod(-1) - inter
        best = jnp.argmax(inter / jnp.maximum(union, 1e-10), axis=-1)
        mask_arr = jnp.asarray(mask)
        own = (best[..., None] == mask_arr[None, None, :])  # [B,G,na]
        sel = valid[..., None] & own

        # ignore_thresh (reference yolov3_loss_op): decode every predicted
        # box and drop the no-object penalty where its best IoU against
        # any gt exceeds the threshold — those cells are "almost right",
        # not negatives.
        gxn = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
        gyn = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
        sx = sxy * jax.nn.sigmoid(tx) - 0.5 * (sxy - 1.0)
        sy = sxy * jax.nn.sigmoid(ty) - 0.5 * (sxy - 1.0)
        pcx = (sx + gxn) / w
        pcy = (sy + gyn) / h
        paw = sub[:, 0][None, :, None, None]
        pah = sub[:, 1][None, :, None, None]
        pw_ = jnp.exp(jnp.clip(tw, -10, 10)) * paw / (w * ds)
        ph_ = jnp.exp(jnp.clip(th, -10, 10)) * pah / (h * ds)
        pred_box = jnp.stack([pcx - pw_ / 2, pcy - ph_ / 2,
                              pcx + pw_ / 2, pcy + ph_ / 2], -1)
        gt_xyxy = jnp.stack([gtb[:, :, 0] - gw / 2, gtb[:, :, 1] - gh / 2,
                             gtb[:, :, 0] + gw / 2, gtb[:, :, 1] + gh / 2],
                            -1)                          # [B,G,4]
        pb = pred_box.reshape(b, -1, 4)                  # [B,naHW,4]
        lt = jnp.maximum(pb[:, :, None, :2], gt_xyxy[:, None, :, :2])
        rb = jnp.minimum(pb[:, :, None, 2:], gt_xyxy[:, None, :, 2:])
        whi = jnp.clip(rb - lt, 0)
        inter_p = whi[..., 0] * whi[..., 1]
        area_p = ((pb[:, :, 2] - pb[:, :, 0]) *
                  (pb[:, :, 3] - pb[:, :, 1]))[:, :, None]
        area_g = (gw * gh)[:, None, :]
        iou_pg = inter_p / jnp.maximum(area_p + area_g - inter_p, 1e-10)
        iou_pg = jnp.where(valid[:, None, :], iou_pg, 0.0)
        best_iou = jnp.max(iou_pg, axis=2).reshape(b, na, h, w)

        obj_target = jnp.zeros((b, na, h, w))
        cls_target = jnp.zeros((b, na, num_classes, h, w))
        box_w = jnp.zeros((b, na, h, w))
        txt = jnp.zeros((b, na, h, w))
        tyt = jnp.zeros((b, na, h, w))
        twt = jnp.zeros((b, na, h, w))
        tht = jnp.zeros((b, na, h, w))
        bidx = jnp.arange(b)[:, None, None]
        aidx = jnp.arange(na)[None, None, :]
        bb = jnp.broadcast_to(bidx, sel.shape)
        aa = jnp.broadcast_to(aidx, sel.shape)
        jj = jnp.broadcast_to(cj[..., None], sel.shape)
        ii = jnp.broadcast_to(ci[..., None], sel.shape)
        selw = sel.astype(jnp.float32)
        # per-gt mixup score rides every positive contribution
        # (yolov3_loss_op.h:390 — score multiplies the gt's terms)
        selws = selw * jnp.broadcast_to(gts[..., None], sel.shape)
        obj_target = obj_target.at[bb, aa, jj, ii].max(selw)
        # with scale_x_y, the sigmoid target solves
        # s·sig(t) − (s−1)/2 = frac  →  sig(t) = (frac + (s−1)/2)/s
        fx = (gx - jnp.floor(gx) + 0.5 * (sxy - 1.0)) / sxy
        fy = (gy - jnp.floor(gy) + 0.5 * (sxy - 1.0)) / sxy
        txt = txt.at[bb, aa, jj, ii].add(
            selw * jnp.broadcast_to(
                jnp.clip(fx, 0.0, 1.0)[..., None], sel.shape))
        tyt = tyt.at[bb, aa, jj, ii].add(
            selw * jnp.broadcast_to(
                jnp.clip(fy, 0.0, 1.0)[..., None], sel.shape))
        aw = sub[:, 0][None, None, :]
        ah = sub[:, 1][None, None, :]
        twt = twt.at[bb, aa, jj, ii].add(
            selw * jnp.log(jnp.maximum(
                gw[..., None] * w * ds / aw, 1e-9)))
        tht = tht.at[bb, aa, jj, ii].add(
            selw * jnp.log(jnp.maximum(
                gh[..., None] * h * ds / ah, 1e-9)))
        box_w = box_w.at[bb, aa, jj, ii].max(selws)
        cls_oh = jax.nn.one_hot(jnp.clip(gtl, 0), num_classes)  # [B,G,C]
        smooth_oh = cls_oh * label_pos + (1.0 - cls_oh) * label_neg
        cls_target = cls_target.at[
            bb, aa, :, jj, ii].max(selw[..., None] *
                                   jnp.broadcast_to(
                                       smooth_oh[:, :, None],
                                       sel.shape + (num_classes,)))

        bce = lambda logit, tgt, wgt: jnp.sum(
            wgt * (jnp.maximum(logit, 0) - logit * tgt +
                   jnp.log1p(jnp.exp(-jnp.abs(logit)))))
        loss_xy = bce(tx, txt, box_w) + bce(ty, tyt, box_w)
        loss_wh = jnp.sum(box_w * ((tw - twt) ** 2 + (th - tht) ** 2)) * 0.5
        # objectness: positives count at their gt score; negatives only
        # where the best IoU vs gt stays below ignore_thresh
        obj_w = jnp.where(obj_target > 0, jnp.maximum(box_w, 1e-8),
                          (best_iou < ignore_thresh).astype(jnp.float32))
        loss_obj = bce(tobj, obj_target, obj_w)
        loss_cls = bce(tcls, cls_target,
                       jnp.broadcast_to(box_w[:, :, None], cls_target.shape))
        return loss_xy + loss_wh + loss_obj + loss_cls

    import jax

    def f(gtb, gtl, gts, *preds):
        total = 0.0
        for pred, mask, ds in zip(preds, anchor_masks, downsample_ratios):
            total = total + one_level(pred, gtb, gtl, gts, mask, ds)
        return total / preds[0].shape[0]
    from ...core.tensor import to_tensor as tt
    if gt_scores is None:
        gt_arr = (gt_boxes.numpy() if hasattr(gt_boxes, "numpy")
                  else gt_boxes)
        gt_scores = np.ones(np.asarray(gt_arr).shape[:2], np.float32)
    tensors = (gt_boxes, gt_labels, gt_scores) + tuple(outputs)
    return apply("yolov3_loss", f,
                 tuple(t if isinstance(t, Tensor) else tt(t)
                       for t in tensors))
