"""Detection ops (reference paddle/fluid/operators/detection/, 18.2k LoC
CUDA/C++, surfaced as paddle.vision.ops + fluid.layers.detection).

TPU-native design: every op is a fixed-shape masked dense computation —
NMS keeps a static ``keep`` mask instead of compacting (XLA-friendly; the
caller slices by the returned count), yolo_box decodes the whole grid in
one vectorized pass, roi_align is a gather+bilinear composition.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd.engine import apply
from ..core.tensor import Tensor, to_tensor

__all__ = ["iou", "box_coder", "yolo_box", "nms", "multiclass_nms",
           "matrix_nms", "roi_align", "roi_pool", "prior_box",
           "generate_anchors", "distribute_fpn_proposals"]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _pairwise_iou(a, b):
    """a: [N,4], b: [M,4] xyxy → [N,M]."""
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.clip(a[:, 2] - a[:, 0], 0) * jnp.clip(a[:, 3] - a[:, 1], 0)
    area_b = jnp.clip(b[:, 2] - b[:, 0], 0) * jnp.clip(b[:, 3] - b[:, 1], 0)
    union = area_a[:, None] + area_b[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


def iou(boxes1, boxes2, name=None):
    """Pairwise IoU, xyxy (reference iou_similarity_op)."""
    return apply("iou", _pairwise_iou, (_t(boxes1), _t(boxes2)))


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Encode/decode boxes against priors (reference box_coder_op)."""

    def f(prior, var, target):
        n1 = 0.0 if box_normalized else 1.0
        pw = prior[:, 2] - prior[:, 0] + n1          # [M]
        ph = prior[:, 3] - prior[:, 1] + n1
        pcx = prior[:, 0] + pw * 0.5
        pcy = prior[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            # reference semantics: every target encoded against every
            # prior → [N, M, 4]
            tw = target[:, 2] - target[:, 0] + n1    # [N]
            th = target[:, 3] - target[:, 1] + n1
            tcx = target[:, 0] + tw * 0.5
            tcy = target[:, 1] + th * 0.5
            out = jnp.stack(
                [(tcx[:, None] - pcx[None, :]) / pw[None, :],
                 (tcy[:, None] - pcy[None, :]) / ph[None, :],
                 jnp.log(tw[:, None] / pw[None, :]),
                 jnp.log(th[:, None] / ph[None, :])], axis=2)
            if var is not None:
                vb = var[None, :, :] if var.ndim == 2 else \
                    var.reshape(1, 1, 4)
                out = out / vb
            return out
        # decode_center_size: target [N, M, 4]; `axis` names the target
        # axis the [*, 4] prior broadcasts ALONG (reference box_coder_op:
        # axis=0 → prior aligns with dim 1, axis=1 → with dim 0)
        if target.ndim == 3:
            exp = (lambda a: a[None, :]) if axis == 0 else \
                (lambda a: a[:, None])
        else:
            exp = lambda a: a
        if var is not None:
            if var.ndim == 2:
                vb = (var[None, :, :] if axis == 0 else var[:, None, :]) \
                    if target.ndim == 3 else var
            else:
                vb = var.reshape((1,) * (target.ndim - 1) + (4,))
            t = target * vb
        else:
            t = target
        cx = t[..., 0] * exp(pw) + exp(pcx)
        cy = t[..., 1] * exp(ph) + exp(pcy)
        w = jnp.exp(t[..., 2]) * exp(pw)
        h = jnp.exp(t[..., 3]) * exp(ph)
        return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                         axis=-1)
    if prior_box_var is None:
        return apply("box_coder", lambda p, t: f(p, None, t),
                     (_t(prior_box), _t(target_box)))
    return apply("box_coder", f,
                 (_t(prior_box), _t(prior_box_var), _t(target_box)))


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    """Decode one YOLO head feature map into boxes+scores (reference
    yolo_box_op). x: [B, na*(5+C), H, W]; returns (boxes [B, na*H*W, 4],
    scores [B, na*H*W, C])."""
    anchors = np.asarray(anchors, np.float32).reshape(-1, 2)
    na = anchors.shape[0]

    def f(x, img_size):
        b, _, h, w = x.shape
        pred = x.reshape(b, na, 5 + class_num + (1 if iou_aware else 0),
                         h, w)
        if iou_aware:
            ioup = jax.nn.sigmoid(pred[:, :, -1])
            pred = pred[:, :, :-1]
        gx = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
        gy = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
        sx = jax.nn.sigmoid(pred[:, :, 0]) * scale_x_y - (scale_x_y - 1) / 2
        sy = jax.nn.sigmoid(pred[:, :, 1]) * scale_x_y - (scale_x_y - 1) / 2
        cx = (sx + gx) / w
        cy = (sy + gy) / h
        aw = anchors[:, 0][None, :, None, None]
        ah = anchors[:, 1][None, :, None, None]
        bw = jnp.exp(pred[:, :, 2]) * aw / (w * downsample_ratio)
        bh = jnp.exp(pred[:, :, 3]) * ah / (h * downsample_ratio)
        obj = jax.nn.sigmoid(pred[:, :, 4])
        if iou_aware:
            obj = obj ** (1 - iou_aware_factor) * ioup ** iou_aware_factor
        cls = jax.nn.sigmoid(pred[:, :, 5:])           # [B,na,C,H,W]
        scores = jnp.where(obj[:, :, None] > conf_thresh,
                           obj[:, :, None] * cls, 0.0)
        imh = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
        imw = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
        x0 = (cx - bw / 2) * imw
        y0 = (cy - bh / 2) * imh
        x1 = (cx + bw / 2) * imw
        y1 = (cy + bh / 2) * imh
        if clip_bbox:
            x0 = jnp.clip(x0, 0, imw - 1)
            y0 = jnp.clip(y0, 0, imh - 1)
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
        boxes = jnp.stack([x0, y0, x1, y1], axis=-1)   # [B,na,H,W,4]
        boxes = boxes.reshape(b, na * h * w, 4)
        scores = scores.transpose(0, 1, 3, 4, 2).reshape(
            b, na * h * w, class_num)
        return boxes, scores
    return apply("yolo_box", f, (_t(x), _t(img_size)))


def _nms_mask(boxes, scores, iou_threshold, top_k):
    """Greedy hard-NMS as a fixed-iteration masked loop (XLA-friendly:
    no dynamic shapes). Returns keep mask [N] bool."""
    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    boxes_s = boxes[order]
    ious = _pairwise_iou(boxes_s, boxes_s)

    def body(i, keep):
        # suppress j>i overlapping an already-kept i; the loop must cover
        # ALL ranks (top_k is applied at selection time by the caller, not
        # by truncating suppression)
        sup = (ious[i] > iou_threshold) & keep[i] & \
            (jnp.arange(n) > i)
        return keep & ~sup
    keep_sorted = jax.lax.fori_loop(0, n, body, jnp.ones(n, bool))
    keep = jnp.zeros(n, bool).at[order].set(keep_sorted)
    return keep


def _iou_matrix_np(b):
    """Pairwise IoU on host (numpy twin of _pairwise_iou)."""
    area = np.maximum(b[:, 2] - b[:, 0], 0) * np.maximum(b[:, 3] - b[:, 1], 0)
    x0 = np.maximum(b[:, None, 0], b[None, :, 0])
    y0 = np.maximum(b[:, None, 1], b[None, :, 1])
    x1 = np.minimum(b[:, None, 2], b[None, :, 2])
    y1 = np.minimum(b[:, None, 3], b[None, :, 3])
    inter = np.maximum(x1 - x0, 0) * np.maximum(y1 - y0, 0)
    union = area[:, None] + area[None, :] - inter
    return inter / np.maximum(union, 1e-10)


def _nms_keep_np(boxes, scores, iou_threshold):
    """Greedy hard-NMS on host; returns keep mask [N] (numpy twin of
    _nms_mask — postprocess runs beside the input pipeline, not on the
    device: each eager device op through a remote chip costs a round
    trip, which made per-class NMS pathologically slow)."""
    n = boxes.shape[0]
    order = np.argsort(-scores)
    ious = _iou_matrix_np(boxes[order])
    keep_sorted = np.ones(n, bool)
    rng = np.arange(n)
    for i in range(n):
        if keep_sorted[i]:
            keep_sorted &= ~((ious[i] > iou_threshold) & (rng > i))
    keep = np.zeros(n, bool)
    keep[order] = keep_sorted
    return keep


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Hard NMS (reference nms_op / paddle.vision.ops.nms). Returns kept
    indices sorted by score (eager: exact compaction)."""
    b = _t(boxes)
    s = _t(scores) if scores is not None else to_tensor(
        np.arange(b.shape[0], 0, -1).astype(np.float32))

    import jax.core as _jcore
    cat_t = _t(category_idxs) if category_idxs is not None else None
    concrete = not any(isinstance(t.data, _jcore.Tracer)
                       for t in (b, s, cat_t) if t is not None)
    if concrete:
        bn = np.asarray(b.numpy())
        sn = np.asarray(s.numpy())
        if cat_t is not None:
            c = np.asarray(cat_t.numpy()).astype(np.float32)
            span = bn.max() - bn.min() + 1.0
            bn = bn + c[:, None] * span
        keep_np = _nms_keep_np(bn, sn, iou_threshold)
        idx = np.nonzero(keep_np)[0]
        idx = idx[np.argsort(-sn[idx])]
        if top_k is not None:
            idx = idx[:top_k]
        return to_tensor(idx.astype(np.int64))

    def f(boxes, scores, *cat):
        if cat:
            # category-aware: offset boxes per category so cross-category
            # pairs never overlap (the standard batched-NMS trick)
            c = cat[0].astype(jnp.float32)
            # offset by the full coordinate SPAN so categories land in
            # disjoint bands even when coordinates are negative (a plain
            # max+1 offset fails to separate then — ADVICE r1 finding)
            span = jnp.max(boxes) - jnp.min(boxes) + 1.0
            off = c[:, None] * span
            keep = _nms_mask(boxes + off, scores, iou_threshold,
                             top_k or 0)
        else:
            keep = _nms_mask(boxes, scores, iou_threshold, top_k or 0)
        return keep
    cat_args = (_t(category_idxs),) if category_idxs is not None else ()
    keep = apply("nms", f, (b, s) + cat_args)
    keep_np = np.asarray(keep.numpy())
    scores_np = np.asarray(s.numpy())
    idx = np.nonzero(keep_np)[0]
    idx = idx[np.argsort(-scores_np[idx])]
    if top_k is not None:
        idx = idx[:top_k]
    return to_tensor(idx.astype(np.int64))


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=400,
                   keep_top_k=100, nms_threshold=0.45, normalized=True,
                   background_label=-1, name=None):
    """Per-class NMS + global top-k (reference multiclass_nms_op).
    bboxes: [N, 4]; scores: [C, N] (single image) → [M, 6]
    (label, score, x0, y0, x1, y1)."""
    b = np.asarray(_t(bboxes).numpy())
    s = np.asarray(_t(scores).numpy())
    out = []
    for c in range(s.shape[0]):
        if c == background_label:
            continue
        cs = s[c]
        sel = cs > score_threshold
        if not sel.any():
            continue
        idx = np.nonzero(sel)[0]
        idx = idx[np.argsort(-cs[idx])][:nms_top_k]
        # host path end-to-end: no device round-trips per class
        keep_mask = _nms_keep_np(b[idx], cs[idx], nms_threshold)
        keep_rel = np.nonzero(keep_mask)[0]
        keep_rel = keep_rel[np.argsort(-cs[idx][keep_rel])]
        for i in keep_rel:
            gi = idx[i]
            out.append([float(c), float(cs[gi])] + b[gi].tolist())
    if not out:
        return to_tensor(np.zeros((0, 6), np.float32))
    out = np.asarray(out, np.float32)
    out = out[np.argsort(-out[:, 1])][:keep_top_k]
    return to_tensor(out)


def matrix_nms(bboxes, scores, score_threshold=0.05, post_threshold=0.0,
               nms_top_k=400, keep_top_k=100, use_gaussian=False,
               gaussian_sigma=2.0, background_label=-1, normalized=True,
               name=None):
    """Matrix NMS (reference matrix_nms_op — SOLOv2/PP-YOLOE): fully
    parallel score decay, no sequential suppression. Single image:
    bboxes [N,4], scores [C,N] → [M,6]."""

    def f(boxes, scores):
        c, n = scores.shape
        flat = scores.reshape(-1)
        k = min(nms_top_k, flat.shape[0])
        top_scores, top_idx = jax.lax.top_k(flat, k)
        cls = (top_idx // n).astype(jnp.int32)
        box_i = top_idx % n
        bx = boxes[box_i]
        ious = _pairwise_iou(bx, bx)
        same = (cls[:, None] == cls[None, :])
        upper = jnp.triu(jnp.ones((k, k), bool), 1)
        decay_iou = jnp.where(same & upper.T, ious, 0.0)  # j<i kept pairs
        max_iou = jnp.max(decay_iou, axis=1)
        if use_gaussian:
            decay = jnp.min(jnp.where(
                same & upper.T,
                jnp.exp(-(ious ** 2 - max_iou[None, :] ** 2) /
                        gaussian_sigma), 1.0), axis=1)
        else:
            comp = jnp.where(same & upper.T,
                             (1 - ious) / jnp.maximum(1 - max_iou[None, :],
                                                      1e-10), 1.0)
            decay = jnp.min(comp, axis=1)
        dec_scores = top_scores * decay
        valid = (top_scores > score_threshold) & \
            (dec_scores > post_threshold)
        dec_scores = jnp.where(valid, dec_scores, -1.0)
        return dec_scores, cls, bx
    dec, cls, bx = apply("matrix_nms", f, (_t(bboxes), _t(scores)))
    d = np.asarray(dec.numpy())
    order = np.argsort(-d)[:keep_top_k]
    order = order[d[order] > 0]
    rows = np.concatenate([
        np.asarray(cls.numpy())[order, None].astype(np.float32),
        d[order, None],
        np.asarray(bx.numpy())[order]], axis=1)
    return to_tensor(rows.astype(np.float32))


def roi_align(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None, _reduce="mean"):
    """RoIAlign (reference roi_align_op). x: [B,C,H,W]; boxes: [R,4] xyxy
    in input-image coords; boxes_num: rois per image."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    if boxes_num is None:
        nums = None            # all RoIs belong to image 0
    else:
        nums = np.asarray(boxes_num.numpy()
                          if isinstance(boxes_num, Tensor) else boxes_num)
    sr = sampling_ratio if sampling_ratio > 0 else 2
    reduce_max = _reduce == "max"

    def f(feat, rois):
        b, ch, H, W = feat.shape
        if nums is None:
            img_of_roi = np.zeros(rois.shape[0], np.int32)
        else:
            img_of_roi = np.repeat(np.arange(len(nums)), nums)
        off = 0.5 if aligned else 0.0
        x0 = rois[:, 0] * spatial_scale - off
        y0 = rois[:, 1] * spatial_scale - off
        x1 = rois[:, 2] * spatial_scale - off
        y1 = rois[:, 3] * spatial_scale - off
        rw = jnp.maximum(x1 - x0, 1e-3)
        rh = jnp.maximum(y1 - y0, 1e-3)
        # sample grid: oh*sr x ow*sr points per roi
        py = (jnp.arange(oh * sr) + 0.5) / (oh * sr)
        px = (jnp.arange(ow * sr) + 0.5) / (ow * sr)
        sy = y0[:, None] + rh[:, None] * py[None, :]     # [R, oh*sr]
        sx = x0[:, None] + rw[:, None] * px[None, :]     # [R, ow*sr]

        def bilinear(img, ys, xs):
            # img [C,H,W]; ys [hs], xs [ws] → [C,hs,ws]
            y0i = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, H - 1)
            x0i = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, W - 1)
            y1i = jnp.clip(y0i + 1, 0, H - 1)
            x1i = jnp.clip(x0i + 1, 0, W - 1)
            wy = jnp.clip(ys - y0i, 0, 1)[None, :, None]
            wx = jnp.clip(xs - x0i, 0, 1)[None, None, :]
            a = img[:, y0i][:, :, x0i]
            bq = img[:, y0i][:, :, x1i]
            cq = img[:, y1i][:, :, x0i]
            dq = img[:, y1i][:, :, x1i]
            top = a * (1 - wx) + bq * wx
            bot = cq * (1 - wx) + dq * wx
            return top * (1 - wy) + bot * wy

        outs = []
        for r in range(rois.shape[0]):
            img = feat[int(img_of_roi[r])]
            samp = bilinear(img, sy[r], sx[r])           # [C, oh*sr, ow*sr]
            samp = samp.reshape(ch, oh, sr, ow, sr)
            outs.append(samp.max(axis=(2, 4)) if reduce_max
                        else samp.mean(axis=(2, 4)))
        return jnp.stack(outs) if outs else jnp.zeros((0, ch, oh, ow),
                                                      feat.dtype)
    return apply("roi_align", f, (_t(x), _t(boxes)))


def roi_pool(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0,
             name=None):
    """Max RoI pooling (reference roi_pool_op): bilinear sample grid with
    MAX reduction per output bin (roi_align's sampling replaces the
    legacy hard quantization; the reduction stays max)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    return roi_align(x, boxes, boxes_num, output_size, spatial_scale,
                     sampling_ratio=2, aligned=False, _reduce="max")


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None):
    """SSD prior boxes for one feature map (reference prior_box_op).
    Returns (boxes [H,W,P,4], variances [H,W,P,4])."""
    inp, img = _t(input), _t(image)
    fh, fw = inp.shape[2], inp.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    step_h = steps[1] or ih / fh
    step_w = steps[0] or iw / fw
    ars = list(aspect_ratios)
    if flip:
        ars += [1.0 / a for a in aspect_ratios if a != 1.0]
    boxes = []
    for ms in min_sizes:
        boxes.append((ms, ms))
        if max_sizes:
            for xs in max_sizes:
                boxes.append((float(np.sqrt(ms * xs)),) * 2)
        for a in ars:
            if a == 1.0:
                continue
            boxes.append((ms * np.sqrt(a), ms / np.sqrt(a)))
    P = len(boxes)
    wh = np.asarray(boxes, np.float32)
    cy = (np.arange(fh) + offset) * step_h
    cx = (np.arange(fw) + offset) * step_w
    cxg, cyg = np.meshgrid(cx, cy)
    out = np.zeros((fh, fw, P, 4), np.float32)
    out[..., 0] = (cxg[..., None] - wh[None, None, :, 0] / 2) / iw
    out[..., 1] = (cyg[..., None] - wh[None, None, :, 1] / 2) / ih
    out[..., 2] = (cxg[..., None] + wh[None, None, :, 0] / 2) / iw
    out[..., 3] = (cyg[..., None] + wh[None, None, :, 1] / 2) / ih
    if clip:
        out = np.clip(out, 0, 1)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          out.shape).copy()
    return to_tensor(out), to_tensor(var)


def generate_anchors(stride, sizes=(32,), aspect_ratios=(0.5, 1.0, 2.0)):
    """Base anchors for one FPN level (anchor_generator_op analog)."""
    anchors = []
    for s in sizes:
        area = float(s) ** 2
        for ar in aspect_ratios:
            w = np.sqrt(area / ar)
            h = w * ar
            anchors.append([-w / 2, -h / 2, w / 2, h / 2])
    return to_tensor(np.asarray(anchors, np.float32))


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None, name=None):
    """Assign RoIs to FPN levels by scale (reference
    distribute_fpn_proposals_op). Returns (rois_per_level list,
    restore_index)."""
    rois = np.asarray(_t(fpn_rois).numpy())
    w = np.maximum(rois[:, 2] - rois[:, 0], 0)
    h = np.maximum(rois[:, 3] - rois[:, 1], 0)
    scale = np.sqrt(w * h)
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    outs, order = [], []
    for l in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == l)[0]
        order.append(idx)
        outs.append(to_tensor(rois[idx]))
    order = np.concatenate(order) if order else np.zeros(0, np.int64)
    restore = np.empty_like(order)
    restore[order] = np.arange(len(order))
    return outs, to_tensor(restore.astype(np.int64))


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1,
                  mask=None, name=None):
    """2.0 functional deformable conv (reference vision/ops.py
    deform_conv2d over deformable_conv_op): explicit ``weight``
    [F, C/groups, kh, kw]; ``mask`` present → v2 (modulated)."""
    from ..fluid.detection_train import deform_conv2d_core
    two = lambda v: tuple(v) if isinstance(v, (list, tuple)) else (v, v)
    return deform_conv2d_core(x, offset, mask, weight, bias,
                              two(stride), two(padding), two(dilation),
                              groups, deformable_groups)


from ..nn.layer_base import Layer as _Layer  # noqa: E402


class DeformConv2D(_Layer):
    """Layer form of deform_conv2d (reference vision/ops.py
    DeformConv2D): owns weight/bias; offsets (and the v2 mask) are
    inputs computed by a sibling conv. A real nn.Layer so an enclosing
    model registers it (parameters/state_dict)."""

    def __init__(self, in_channels, out_channels, kernel_size,
                 stride=1, padding=0, dilation=1, deformable_groups=1,
                 groups=1, weight_attr=None, bias_attr=None):
        super().__init__()
        kh, kw = (kernel_size if isinstance(kernel_size, (list, tuple))
                  else (kernel_size, kernel_size))
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, kh, kw],
            attr=weight_attr)
        self.bias = (self.create_parameter([out_channels],
                                           is_bias=True,
                                           attr=bias_attr)
                     if bias_attr is not False else None)
        self._cfg = (stride, padding, dilation, deformable_groups,
                     groups)

    def forward(self, x, offset, mask=None):
        s, p, d, dg, g = self._cfg
        return deform_conv2d(x, offset, self.weight, self.bias,
                             stride=s, padding=p, dilation=d,
                             deformable_groups=dg, groups=g, mask=mask)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """Single-level YOLOv3 loss (reference vision/ops.py yolo_loss /
    yolov3_loss_op): delegates to the multi-level
    vision.models.yolo.yolov3_loss with one output map, forwarding
    gt_score (mixup weights), label smoothing, and scale_x_y. Returns
    the scalar loss (this build reduces over the batch; the reference
    returns per-sample [N])."""
    from .models.yolo import yolov3_loss
    return yolov3_loss([x], gt_box, gt_label,
                       anchors=[list(a) if isinstance(a, (list, tuple))
                                else a for a in
                                np.asarray(anchors).reshape(-1, 2)
                                .tolist()],
                       anchor_masks=[list(anchor_mask)],
                       num_classes=class_num,
                       ignore_thresh=ignore_thresh,
                       downsample_ratios=(downsample_ratio,),
                       gt_scores=gt_score,
                       use_label_smooth=use_label_smooth,
                       scale_x_y=scale_x_y)


def read_file(filename, name=None):
    """Raw file bytes as a uint8 tensor (reference vision/ops.py
    read_file; pairs with decode_jpeg)."""
    from ..fluid.misc_tail import read_file as _impl
    return _impl(filename, name=name)


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to [C, H, W] uint8 (reference
    vision/ops.py decode_jpeg over nvjpeg). Pure-numpy baseline
    decoder (core/jpeg.py): sequential baseline DCT, the format the
    reference's pipeline produces/consumes."""
    from ..core.jpeg import decode_jpeg_bytes
    data = np.asarray(_t(x).numpy(), np.uint8).tobytes()
    img = decode_jpeg_bytes(data)  # [H, W, C] uint8
    if mode == "gray" and img.shape[-1] == 3:
        img = (0.299 * img[..., 0] + 0.587 * img[..., 1]
               + 0.114 * img[..., 2]).astype(np.uint8)[..., None]
    from ..core.tensor import to_tensor
    return to_tensor(np.ascontiguousarray(img.transpose(2, 0, 1)))


__all__ += ["deform_conv2d", "DeformConv2D", "yolo_loss", "read_file",
            "decode_jpeg"]
