"""Builtin vision datasets (reference python/paddle/vision/datasets/:
MNIST, FashionMNIST, Cifar10/100, Flowers). This environment has no network
egress, so ``download=True`` raises with instructions; parsers read the
standard archive formats from ``data_file``/``image_path`` like the
reference. ``FakeData`` provides deterministic synthetic images so examples
and tests run hermetically (the simulated-data analog of SURVEY §4's
simulated-mesh backend)."""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ..io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeData"]


def _no_download(name):
    raise RuntimeError(
        f"{name}: automatic download is unavailable (no network egress). "
        "Place the official archive locally and pass its path "
        "(image_path/label_path or data_file).")


class MNIST(Dataset):
    """IDX-format parser (reference vision/datasets/mnist.py)."""

    NAME = "MNIST"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend="cv2"):
        self.mode = mode.lower()
        self.transform = transform
        if image_path is None or label_path is None:
            _no_download(self.NAME)
        self.images = self._parse_images(image_path)
        self.labels = self._parse_labels(label_path)

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") else \
            open(path, "rb")

    def _parse_images(self, path):
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            assert magic == 2051, f"bad MNIST image magic {magic}"
            data = np.frombuffer(f.read(n * rows * cols), np.uint8)
            return data.reshape(n, rows, cols)

    def _parse_labels(self, path):
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            assert magic == 2049, f"bad MNIST label magic {magic}"
            return np.frombuffer(f.read(n), np.uint8).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        label = self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array([label], np.int64)

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "FashionMNIST"


class Cifar10(Dataset):
    """cifar-10-python.tar.gz parser (reference vision/datasets/cifar.py)."""

    NAME = "Cifar10"
    _SUB = {"train": "data_batch", "test": "test_batch"}

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend="cv2"):
        self.mode = mode.lower()
        self.transform = transform
        if data_file is None:
            _no_download(self.NAME)
        self.data = []
        with tarfile.open(data_file, "r:*") as tf:
            names = [n for n in tf.getnames()
                     if self._SUB[self.mode] in n]
            for name in sorted(names):
                batch = pickle.load(tf.extractfile(name), encoding="bytes")
                images = batch[b"data"].reshape(-1, 3, 32, 32)
                labels = batch.get(b"labels", batch.get(b"fine_labels"))
                for img, lab in zip(images, labels):
                    self.data.append((img, lab))

    def __getitem__(self, idx):
        img, label = self.data[idx]
        img = img.transpose(1, 2, 0)  # HWC for transforms
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array([label], np.int64)

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    NAME = "Cifar100"
    _SUB = {"train": "train", "test": "test"}


class FakeData(Dataset):
    """Deterministic synthetic image classification data (hermetic tests)."""

    def __init__(self, num_samples=256, image_shape=(3, 32, 32),
                 num_classes=10, transform=None, seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self._rng = np.random.default_rng(seed)
        self._images = self._rng.integers(
            0, 256, (num_samples,) + self.image_shape[1:] +
            (self.image_shape[0],), dtype=np.uint8)
        self._labels = self._rng.integers(0, num_classes, num_samples)

    def __getitem__(self, idx):
        img = self._images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array([self._labels[idx]], np.int64)

    def __len__(self):
        return self.num_samples


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp")


def _pil_loader(path):
    from PIL import Image
    with open(path, "rb") as f:
        img = Image.open(f)
        return img.convert("RGB")


def _extension_checker(extensions, is_valid_file):
    """One place to normalize the extension filter for both folder
    datasets. Returns (checker, normalized_extensions_or_None) — None
    when a custom is_valid_file decides (extensions never consulted).
    A lone string must NOT go through tuple(): tuple('.png') is
    ('.', 'p', 'n', 'g') and matches nearly everything."""
    if is_valid_file is not None:
        return is_valid_file, None
    if extensions is None:
        exts = IMG_EXTENSIONS
    elif isinstance(extensions, str):
        exts = (extensions,)
    else:
        exts = tuple(extensions)

    def check(p):
        return p.lower().endswith(exts)
    return check, exts


class DatasetFolder(Dataset):
    """Class-per-subdirectory image tree (reference vision/datasets/
    folder.py:65): root/class_x/xxx.png → (sample, class_index)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or _pil_loader
        is_valid_file, exts = _extension_checker(extensions, is_valid_file)
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise RuntimeError(f"no class folders under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for sub, _, files in sorted(os.walk(cdir)):
                for fn in sorted(files):
                    p = os.path.join(sub, fn)
                    if is_valid_file(p):
                        self.samples.append((p, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(
                f"found 0 files in subfolders of {root}; "
                + (f"supported extensions: {exts}" if exts is not None
                   else "the custom is_valid_file accepted nothing"))

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat/recursive unlabeled image folder (reference folder.py:222):
    yields [sample] per image."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or _pil_loader
        is_valid_file, _ = _extension_checker(extensions, is_valid_file)
        self.samples = []
        for sub, _, files in sorted(os.walk(root)):
            for fn in sorted(files):
                p = os.path.join(sub, fn)
                if is_valid_file(p):
                    self.samples.append(p)
        if not self.samples:
            raise RuntimeError(f"found 0 images under {root}")

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)


class Flowers(Dataset):
    """Oxford 102 Flowers (reference vision/datasets/flowers.py):
    102flowers.tgz image archive + imagelabels.mat + setid.mat; the
    split comes from setid's trnid/valid/tstid index lists (1-based)."""

    _SETID_KEY = {"train": "trnid", "valid": "valid", "test": "tstid"}

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None):
        if None in (data_file, label_file, setid_file):
            _no_download("Flowers")
        if mode not in self._SETID_KEY:
            raise ValueError("mode must be train/valid/test")
        import scipy.io as sio
        self.transform = transform
        labels = sio.loadmat(label_file)["labels"].reshape(-1)
        indexes = sio.loadmat(setid_file)[
            self._SETID_KEY[mode]].reshape(-1)
        wanted = {f"jpg/image_{int(i):05d}.jpg": int(i) for i in indexes}
        # one sequential pass over the archive, keeping the COMPRESSED
        # jpeg bytes per sample: picklable for DataLoader workers, no
        # shared fd, no per-__getitem__ gzip rewind (a .tgz member seek
        # re-decompresses from the stream start)
        self.samples = []
        with tarfile.open(data_file) as tar:
            for m in tar:
                i = wanted.get(m.name)
                if i is not None:
                    self.samples.append((tar.extractfile(m).read(),
                                         int(labels[i - 1])))

    def __getitem__(self, idx):
        from PIL import Image
        import io as _io
        raw, label = self.samples[idx]
        img = Image.open(_io.BytesIO(raw)).convert("RGB")
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array([label], np.int64)

    def __len__(self):
        return len(self.samples)


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation pairs (reference vision/datasets/
    voc2012.py): the trainval archive's ImageSets/Segmentation lists
    select JPEGImages/x.jpg + SegmentationClass/x.png; yields
    (image HWC uint8 array, label mask HW uint8 array)."""

    _ROOT = "VOCdevkit/VOC2012"

    def __init__(self, data_file=None, mode="train", transform=None):
        if data_file is None:
            _no_download("VOC2012")
        if mode not in ("train", "val", "trainval"):
            raise ValueError("mode must be train/val/trainval")
        self.transform = transform
        with tarfile.open(data_file) as tar:
            listing = tar.extractfile(
                f"{self._ROOT}/ImageSets/Segmentation/{mode}.txt")
            names = [ln.strip() for ln in
                     listing.read().decode().splitlines() if ln.strip()]
            blobs = {}
            want = set()
            for n in names:
                want.add(f"{self._ROOT}/JPEGImages/{n}.jpg")
                want.add(f"{self._ROOT}/SegmentationClass/{n}.png")
            for m in tar:
                if m.name in want:
                    blobs[m.name] = tar.extractfile(m).read()
        # compressed bytes in memory (see Flowers): worker-safe + one pass
        self.samples = []
        for n in names:
            jpg = blobs.get(f"{self._ROOT}/JPEGImages/{n}.jpg")
            png = blobs.get(f"{self._ROOT}/SegmentationClass/{n}.png")
            if jpg is not None and png is not None:
                self.samples.append((jpg, png))

    def __getitem__(self, idx):
        from PIL import Image
        import io as _io
        jpg, png = self.samples[idx]
        image = np.asarray(Image.open(_io.BytesIO(jpg)).convert("RGB"))
        label = np.asarray(Image.open(_io.BytesIO(png)))
        if self.transform is not None:
            image = self.transform(image)
        return image, label

    def __len__(self):
        return len(self.samples)


__all__ += ["DatasetFolder", "ImageFolder", "Flowers", "VOC2012",
            "IMG_EXTENSIONS"]
