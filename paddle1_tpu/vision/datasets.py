"""Builtin vision datasets (reference python/paddle/vision/datasets/:
MNIST, FashionMNIST, Cifar10/100, Flowers). This environment has no network
egress, so ``download=True`` raises with instructions; parsers read the
standard archive formats from ``data_file``/``image_path`` like the
reference. ``FakeData`` provides deterministic synthetic images so examples
and tests run hermetically (the simulated-data analog of SURVEY §4's
simulated-mesh backend)."""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ..io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeData"]


def _no_download(name):
    raise RuntimeError(
        f"{name}: automatic download is unavailable (no network egress). "
        "Place the official archive locally and pass its path "
        "(image_path/label_path or data_file).")


class MNIST(Dataset):
    """IDX-format parser (reference vision/datasets/mnist.py)."""

    NAME = "MNIST"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend="cv2"):
        self.mode = mode.lower()
        self.transform = transform
        if image_path is None or label_path is None:
            _no_download(self.NAME)
        self.images = self._parse_images(image_path)
        self.labels = self._parse_labels(label_path)

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") else \
            open(path, "rb")

    def _parse_images(self, path):
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            assert magic == 2051, f"bad MNIST image magic {magic}"
            data = np.frombuffer(f.read(n * rows * cols), np.uint8)
            return data.reshape(n, rows, cols)

    def _parse_labels(self, path):
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            assert magic == 2049, f"bad MNIST label magic {magic}"
            return np.frombuffer(f.read(n), np.uint8).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        label = self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array([label], np.int64)

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "FashionMNIST"


class Cifar10(Dataset):
    """cifar-10-python.tar.gz parser (reference vision/datasets/cifar.py)."""

    NAME = "Cifar10"
    _SUB = {"train": "data_batch", "test": "test_batch"}

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend="cv2"):
        self.mode = mode.lower()
        self.transform = transform
        if data_file is None:
            _no_download(self.NAME)
        self.data = []
        with tarfile.open(data_file, "r:*") as tf:
            names = [n for n in tf.getnames()
                     if self._SUB[self.mode] in n]
            for name in sorted(names):
                batch = pickle.load(tf.extractfile(name), encoding="bytes")
                images = batch[b"data"].reshape(-1, 3, 32, 32)
                labels = batch.get(b"labels", batch.get(b"fine_labels"))
                for img, lab in zip(images, labels):
                    self.data.append((img, lab))

    def __getitem__(self, idx):
        img, label = self.data[idx]
        img = img.transpose(1, 2, 0)  # HWC for transforms
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array([label], np.int64)

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    NAME = "Cifar100"
    _SUB = {"train": "train", "test": "test"}


class FakeData(Dataset):
    """Deterministic synthetic image classification data (hermetic tests)."""

    def __init__(self, num_samples=256, image_shape=(3, 32, 32),
                 num_classes=10, transform=None, seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self._rng = np.random.default_rng(seed)
        self._images = self._rng.integers(
            0, 256, (num_samples,) + self.image_shape[1:] +
            (self.image_shape[0],), dtype=np.uint8)
        self._labels = self._rng.integers(0, num_classes, num_samples)

    def __getitem__(self, idx):
        img = self._images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array([self._labels[idx]], np.int64)

    def __len__(self):
        return self.num_samples
