"""Functional image transforms over HWC numpy arrays (reference
python/paddle/vision/transforms/functional.py + functional_cv2.py).

TPU-native note: these run on the HOST inside DataLoader workers (the
reference does the same with cv2/PIL); device-side augmentation belongs in
the jitted step. Arrays are HWC uint8/float32; CHW tensors come out of
``to_tensor`` at the end of the pipeline.
"""

from __future__ import annotations

import numbers
from typing import Sequence

import numpy as np

__all__ = ["to_tensor", "normalize", "resize", "crop", "center_crop",
           "hflip", "vflip", "pad", "rotate", "adjust_brightness",
           "adjust_contrast", "adjust_saturation", "adjust_hue",
           "to_grayscale"]


def _as_hwc(img) -> np.ndarray:
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def to_tensor(img, data_format="CHW"):
    """HWC uint8 [0,255] → float32 [0,1] tensor (reference
    functional.to_tensor)."""
    from ...core.tensor import to_tensor as tt
    arr = _as_hwc(img)
    if arr.dtype == np.uint8:
        arr = arr.astype(np.float32) / 255.0
    else:
        arr = arr.astype(np.float32)
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return tt(arr)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    from ...core.tensor import Tensor
    if isinstance(img, Tensor):
        arr = img.numpy()
    else:
        arr = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        mean = mean.reshape(-1, 1, 1)
        std = std.reshape(-1, 1, 1)
    out = (arr - mean) / std
    if isinstance(img, Tensor):
        from ...core.tensor import to_tensor as tt
        return tt(out)
    return out


def _interp_resize(arr: np.ndarray, h: int, w: int) -> np.ndarray:
    """Bilinear resize via vectorized numpy (no cv2 dependency)."""
    H, W = arr.shape[:2]
    if (H, W) == (h, w):
        return arr
    ys = (np.arange(h) + 0.5) * H / h - 0.5
    xs = (np.arange(w) + 0.5) * W / w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, H - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, W - 1)
    y1 = np.clip(y0 + 1, 0, H - 1)
    x1 = np.clip(x0 + 1, 0, W - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None, None]
    wx = np.clip(xs - x0, 0, 1)[None, :, None]
    a = arr.astype(np.float32)
    top = a[y0][:, x0] * (1 - wx) + a[y0][:, x1] * wx
    bot = a[y1][:, x0] * (1 - wx) + a[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    if np.issubdtype(arr.dtype, np.floating):
        return out.astype(arr.dtype)
    return np.clip(out + 0.5, 0, 255).astype(arr.dtype)


def resize(img, size, interpolation="bilinear"):
    arr = _as_hwc(img)
    if isinstance(size, numbers.Number):
        H, W = arr.shape[:2]
        if H <= W:
            h, w = int(size), int(size * W / H)
        else:
            h, w = int(size * H / W), int(size)
    else:
        h, w = size
    return _interp_resize(arr, int(h), int(w))


def crop(img, top, left, height, width):
    arr = _as_hwc(img)
    return arr[top:top + height, left:left + width]


def center_crop(img, output_size):
    arr = _as_hwc(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = output_size
    H, W = arr.shape[:2]
    top = max(0, (H - h) // 2)
    left = max(0, (W - w) // 2)
    return crop(arr, top, left, h, w)


def hflip(img):
    return _as_hwc(img)[:, ::-1]


def vflip(img):
    return _as_hwc(img)[::-1]


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = _as_hwc(img)
    if isinstance(padding, numbers.Number):
        pl = pr = pt = pb = int(padding)
    elif len(padding) == 2:
        pl = pr = int(padding[0])
        pt = pb = int(padding[1])
    else:
        pl, pt, pr, pb = [int(p) for p in padding]
    width = ((pt, pb), (pl, pr), (0, 0))
    if padding_mode == "constant":
        return np.pad(arr, width, mode="constant", constant_values=fill)
    mode = {"edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    return np.pad(arr, width, mode=mode)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Nearest-neighbor rotation (reference functional.rotate). Host-side
    augmentation only; device-side use jax.image in the step."""
    arr = _as_hwc(img)
    H, W = arr.shape[:2]
    theta = -np.deg2rad(angle)
    cy, cx = ((H - 1) / 2, (W - 1) / 2) if center is None else center
    yy, xx = np.mgrid[0:H, 0:W]
    ys = cy + (yy - cy) * np.cos(theta) - (xx - cx) * np.sin(theta)
    xs = cx + (yy - cy) * np.sin(theta) + (xx - cx) * np.cos(theta)
    yi = np.round(ys).astype(int)
    xi = np.round(xs).astype(int)
    valid = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
    out = np.full_like(arr, fill)
    out[valid] = arr[yi[valid], xi[valid]]
    return out


def adjust_brightness(img, factor):
    arr = _as_hwc(img).astype(np.float32) * factor
    return np.clip(arr, 0, 255).astype(np.uint8) \
        if np.asarray(img).dtype == np.uint8 else arr


def adjust_contrast(img, factor):
    arr = _as_hwc(img).astype(np.float32)
    mean = arr.mean()
    out = (arr - mean) * factor + mean
    return np.clip(out, 0, 255).astype(np.uint8) \
        if np.asarray(img).dtype == np.uint8 else out


def adjust_saturation(img, factor):
    arr = _as_hwc(img).astype(np.float32)
    gray = arr.mean(axis=2, keepdims=True)
    out = gray + (arr - gray) * factor
    return np.clip(out, 0, 255).astype(np.uint8) \
        if np.asarray(img).dtype == np.uint8 else out


def adjust_hue(img, factor):
    """Hue shift in HSV space, factor ∈ [-0.5, 0.5]."""
    arr = _as_hwc(img)
    dtype = arr.dtype
    a = arr.astype(np.float32) / (255.0 if dtype == np.uint8 else 1.0)
    r, g, b = a[..., 0], a[..., 1], a[..., 2]
    mx, mn = a.max(-1), a.min(-1)
    diff = mx - mn + 1e-12
    h = np.zeros_like(mx)
    m = mx == r
    h[m] = ((g - b)[m] / diff[m]) % 6
    m = mx == g
    h[m] = (b - r)[m] / diff[m] + 2
    m = mx == b
    h[m] = (r - g)[m] / diff[m] + 4
    h = (h / 6.0 + factor) % 1.0
    s = np.where(mx > 0, diff / (mx + 1e-12), 0)
    v = mx
    i = np.floor(h * 6).astype(int) % 6
    f = h * 6 - np.floor(h * 6)
    p, q, t = v * (1 - s), v * (1 - f * s), v * (1 - (1 - f) * s)
    lut = np.stack([np.stack([v, t, p], -1), np.stack([q, v, p], -1),
                    np.stack([p, v, t], -1), np.stack([p, q, v], -1),
                    np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
    out = np.take_along_axis(lut, i[None, ..., None], axis=0)[0]
    if dtype == np.uint8:
        return np.clip(out * 255 + 0.5, 0, 255).astype(np.uint8)
    return out.astype(dtype)


def to_grayscale(img, num_output_channels=1):
    arr = _as_hwc(img).astype(np.float32)
    gray = (arr[..., :3] * np.array([0.299, 0.587, 0.114])).sum(-1,
                                                                keepdims=True)
    gray = np.repeat(gray, num_output_channels, axis=2)
    return gray.astype(np.asarray(img).dtype) \
        if np.asarray(img).dtype != np.uint8 else \
        np.clip(gray + 0.5, 0, 255).astype(np.uint8)
