"""paddle.vision.transforms analog."""

from . import functional
from .transforms import (BaseTransform, BrightnessTransform, CenterCrop,
                         ColorJitter, Compose, ContrastTransform, Grayscale,
                         HueTransform, Normalize, Pad, RandomCrop,
                         RandomHorizontalFlip, RandomResizedCrop,
                         RandomRotation, RandomVerticalFlip, Resize,
                         SaturationTransform, ToTensor, Transpose)

__all__ = ["functional", "BaseTransform", "Compose", "ToTensor", "Normalize",
           "Resize", "RandomCrop", "CenterCrop", "RandomHorizontalFlip",
           "RandomVerticalFlip", "RandomResizedCrop", "RandomRotation",
           "ColorJitter", "Grayscale", "Pad", "Transpose",
           "BrightnessTransform", "ContrastTransform", "SaturationTransform",
           "HueTransform"]
