"""Transform classes (reference python/paddle/vision/transforms/
transforms.py): composable host-side augmentation pipeline."""

from __future__ import annotations

import numbers
import random
from typing import Sequence

import numpy as np

from . import functional as F

__all__ = ["Compose", "BaseTransform", "ToTensor", "Normalize", "Resize",
           "RandomCrop", "CenterCrop", "RandomHorizontalFlip",
           "RandomVerticalFlip", "RandomResizedCrop", "RandomRotation",
           "ColorJitter", "Grayscale", "Pad", "Transpose",
           "BrightnessTransform", "ContrastTransform", "SaturationTransform",
           "HueTransform"]


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data

    def __repr__(self):
        inner = ", ".join(repr(t) for t in self.transforms)
        return f"Compose([{inner}])"


class BaseTransform:
    """Keys-aware base (reference transforms.py BaseTransform); subclasses
    implement _apply_image (and optionally _apply_{label,mask,...})."""

    def __init__(self, keys=None):
        self.keys = keys or ("image",)

    def __call__(self, inputs):
        if isinstance(inputs, tuple):
            out = []
            for key, x in zip(self.keys, inputs):
                fn = getattr(self, f"_apply_{key}", None)
                out.append(fn(x) if fn else x)
            out.extend(inputs[len(self.keys):])
            return tuple(out)
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError

    def __repr__(self):
        return self.__class__.__name__ + "()"


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return F.to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean, self.std = mean, std
        self.data_format = data_format

    def _apply_image(self, img):
        return F.normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return F.resize(img, self.size, self.interpolation)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        if self.padding is not None:
            img = F.pad(img, self.padding, self.fill, self.padding_mode)
        arr = np.asarray(img)
        H, W = arr.shape[:2]
        h, w = self.size
        if self.pad_if_needed and (H < h or W < w):
            img = F.pad(img, (max(0, (w - W + 1) // 2),
                              max(0, (h - H + 1) // 2)),
                        self.fill, self.padding_mode)
            arr = np.asarray(img)
            H, W = arr.shape[:2]
        top = random.randint(0, max(0, H - h))
        left = random.randint(0, max(0, W - w))
        return F.crop(img, top, left, h, w)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return F.center_crop(img, self.size)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return F.hflip(img) if random.random() < self.prob else img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return F.vflip(img) if random.random() < self.prob else img


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = np.asarray(img)
        H, W = arr.shape[:2]
        area = H * W
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]),
                                       np.log(self.ratio[1])))
            w = int(round(np.sqrt(target * ar)))
            h = int(round(np.sqrt(target / ar)))
            if 0 < w <= W and 0 < h <= H:
                top = random.randint(0, H - h)
                left = random.randint(0, W - w)
                patch = F.crop(img, top, left, h, w)
                return F.resize(patch, self.size, self.interpolation)
        return F.resize(F.center_crop(img, min(H, W)), self.size,
                        self.interpolation)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return F.rotate(img, angle, self.interpolation, self.expand,
                        self.center, self.fill)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation
        self.hue = hue

    def _apply_image(self, img):
        ops = []
        if self.brightness:
            f = random.uniform(max(0, 1 - self.brightness),
                               1 + self.brightness)
            ops.append(lambda im: F.adjust_brightness(im, f))
        if self.contrast:
            f2 = random.uniform(max(0, 1 - self.contrast), 1 + self.contrast)
            ops.append(lambda im: F.adjust_contrast(im, f2))
        if self.saturation:
            f3 = random.uniform(max(0, 1 - self.saturation),
                                1 + self.saturation)
            ops.append(lambda im: F.adjust_saturation(im, f3))
        if self.hue:
            f4 = random.uniform(-self.hue, self.hue)
            ops.append(lambda im: F.adjust_hue(im, f4))
        random.shuffle(ops)
        for op in ops:
            img = op(img)
        return img


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return F.to_grayscale(img, self.num_output_channels)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return F.pad(img, self.padding, self.fill, self.padding_mode)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_brightness(img, f)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return F.adjust_hue(img, random.uniform(-self.value, self.value))
