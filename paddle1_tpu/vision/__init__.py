"""paddle1_tpu.vision (reference python/paddle/vision analog)."""

from . import models
