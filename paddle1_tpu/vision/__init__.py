"""paddle1_tpu.vision (reference python/paddle/vision analog)."""

from . import datasets
from . import models
from . import ops
from . import transforms

__all__ = ["datasets", "models", "ops", "transforms"]
