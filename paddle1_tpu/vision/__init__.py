"""paddle1_tpu.vision (reference python/paddle/vision analog)."""

from . import datasets
from . import models
from . import transforms

__all__ = ["datasets", "models", "transforms"]
