"""Automatic mixed precision.

Analog of /root/reference/python/paddle/amp/ (auto_cast → fluid/dygraph/amp/
auto_cast.py:91 amp_guard with WHITE_LIST/BLACK_LIST, GradScaler →
loss_scaler.py:27 AmpScaler) plus the C++ cast insertion in
imperative/amp_auto_cast.cc.

TPU-native: the preferred low-precision dtype is bfloat16 (MXU-native,
exponent range of f32), so overflow-driven loss scaling is usually a no-op —
but the full GradScaler protocol (scale, unscale, inf/nan check,
update_loss_scaling) is implemented for float16 parity and for the
``check_finite`` safety net, mirroring amp/check_finite_and_unscale_op.cu and
amp/update_loss_scaling_op.cu.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.tensor import Tensor, to_tensor
from ..core.errors import InvalidArgumentError

__all__ = ["auto_cast", "amp_guard", "GradScaler", "decorate",
           "WHITE_LIST", "BLACK_LIST", "amp_state"]

# Op categories (reference fluid/dygraph/amp/auto_cast.py:27,36): white =
# always low precision (MXU ops), black = keep f32 (numerically sensitive).
WHITE_LIST = {"matmul", "bmm", "conv1d", "conv2d", "conv3d", "linear",
              "einsum", "addmm", "mv"}
BLACK_LIST = {"exp", "log", "log2", "log10", "mean", "sum", "softmax",
              "log_softmax", "cross_entropy", "layer_norm", "norm",
              "batch_norm_train", "batch_norm_infer", "fused_bn_act_train",
              "fused_bn_act_infer", "cosine_similarity",
              "reduce_sum", "pow", "square", "softmax_with_cross_entropy"}

_tls = threading.local()


class _AmpState:
    def __init__(self, enabled, dtype, level, custom_white, custom_black):
        self.enabled = enabled
        self.dtype = dtype
        self.level = level
        self.white = (WHITE_LIST | set(custom_white or ())) - \
            set(custom_black or ())
        self.black = (BLACK_LIST | set(custom_black or ())) - \
            set(custom_white or ())


def amp_state() -> Optional[_AmpState]:
    return getattr(_tls, "amp", None)


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    """AMP context (reference amp/auto_cast.py:20). Inside, eager ops on the
    white list run in ``dtype``; black-list ops run f32; others follow their
    inputs ('gray' behavior)."""
    if level not in ("O0", "O1", "O2"):
        raise InvalidArgumentError("level must be O0/O1/O2")
    prev = amp_state()
    _tls.amp = _AmpState(enable and level != "O0",
                         dtypes.convert_dtype(dtype), level,
                         custom_white_list, custom_black_list)
    try:
        yield
    finally:
        _tls.amp = prev


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """Pure-low-precision decorate (reference mixed_precision/decorator.py:
    O2 casts parameters)."""
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            m.to(dtype=dtype)
    if optimizers is None:
        return models if single else model_list
    return (models if single else model_list), optimizers


class GradScaler:
    """Loss scaler (reference amp/grad_scaler.py:20 → AmpScaler
    loss_scaler.py:27). Dynamic scaling: double every
    ``incr_every_n_steps`` good steps, halve on inf/nan, skip the step."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False
        self._last_step_skipped = False
        # one dynamic-scale update per detected step outcome: set by
        # unscale_/record_step, consumed by update() — so the reference
        # usage `scaler.step(opt); scaler.update()` (step already
        # updates internally) doesn't register a phantom good step
        self._pending_update = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return self._scale

    def scale(self, var: Tensor) -> Tensor:
        if not self._enable:
            return var
        from ..ops import math_ops
        return math_ops.multiply(var, to_tensor(self._scale,
                                                dtype=var.dtype))

    def unscale_(self, optimizer):
        """check_finite_and_unscale (reference
        amp/check_finite_and_unscale_op.cu): divide grads by scale, flag
        non-finite. Calling it twice before ``step``/``update`` would
        divide the grads by the scale twice — refuse, like the
        reference/torch scalers do."""
        if not self._enable:
            return
        if self._unscaled:
            raise InvalidArgumentError(
                "unscale_() has already been called on this optimizer "
                "since the last update()")
        params = optimizer._parameter_list or []
        found = False
        inv = 1.0 / self._scale
        for p in params:
            if p.grad is None:
                continue
            g = p.grad.data.astype(jnp.float32) * inv
            if not bool(jnp.all(jnp.isfinite(g))):
                found = True
            p.grad._data = g.astype(p.grad.data.dtype)
        self._found_inf = found
        self._unscaled = True
        self._pending_update = True

    def step(self, optimizer):
        """Unscale (if not already), skip the optimizer update when any
        grad came back non-finite, then run the dynamic-scale update.
        ``last_step_skipped()`` reports what happened."""
        if not self._enable:
            optimizer.step()
            self._last_step_skipped = False
            return
        if not self._unscaled:
            self.unscale_(optimizer)
        self._last_step_skipped = self._found_inf
        if not self._found_inf:
            optimizer.step()
        self._unscaled = False
        self.update()

    def minimize(self, optimizer, scaled_loss):
        # reference AmpScaler.minimize: backward already called on the
        # scaled loss by user; unscale, conditional step, update.
        self.step(optimizer)

    def last_step_skipped(self) -> bool:
        """Whether the most recent ``step``/``minimize`` skipped the
        optimizer update because of non-finite grads."""
        return self._last_step_skipped

    def record_step(self, found_inf: bool) -> float:
        """Feed one externally-detected step outcome into the dynamic
        scaling state machine and return the (possibly updated) scale.

        This is the wiring point for compiled training: the engine's
        device-side ``check_finite`` flag (``StepFuture.bad``) already
        says whether the step was applied or skipped on device, so the
        host-side scaler only needs the bookkeeping half of
        update_loss_scaling — halve on a bad step, regrow after
        ``incr_every_n_steps`` good ones — without ever touching
        ``p.grad``.
        """
        self._found_inf = bool(found_inf)
        self._pending_update = True
        self.update()
        return self._scale

    def update(self):
        """update_loss_scaling op logic (reference
        amp/update_loss_scaling_op.cu). One scale update per detected
        step outcome: a call with nothing pending (e.g. the reference
        pattern's external ``update()`` after ``step()`` already
        updated) is a no-op — neither a phantom good step nor a second
        halving."""
        if not self._pending_update:
            return
        self._pending_update = False
        # update() ends the iteration: a manual unscale_/update loop
        # (step skipped by the caller) must be able to unscale_ again
        self._unscaled = False
        if not self._dynamic:
            self._found_inf = False
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_every_n_steps": self._incr_every,
                "decr_every_n_nan_or_inf": self._decr_every,
                "good_steps": self._good_steps,
                "bad_steps": self._bad_steps, "enable": self._enable,
                "use_dynamic_loss_scaling": self._dynamic}

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)
