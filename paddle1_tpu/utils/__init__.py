"""paddle1_tpu.utils — custom-operator extension API (+ misc helpers).

Analog of the reference's out-of-tree operator machinery:
``paddle.utils.cpp_extension`` building a .so that
``framework/custom_operator.cc`` registers into the op registry. The
TPU-native inversion: device compute is authored as jax/Pallas Python
(XLA compiles it for the chip — there is no ABI for hand-built TPU
kernels), so a "custom op" here is a pure function registered into the
tape dispatch, with an optional hand-written backward; *host-side* C/C++
kernels still work, bridged through ``jax.pure_callback`` + ctypes
(:func:`load_op_library`). Both forms run eagerly AND under jit, exactly
like built-in ops.
"""

from __future__ import annotations

import ctypes
from typing import Callable, Dict, Optional, Sequence

import numpy as np

from ..core.errors import InvalidArgumentError

__all__ = ["register_op", "get_op", "registered_ops", "CustomOp",
           "load_op_library", "cpp_extension"]

_REGISTRY: Dict[str, "CustomOp"] = {}


class CustomOp:
    """A registered custom operator.

    ``fwd(*arrays, **attrs)`` — pure jax (jnp / lax / Pallas) function.
    ``bwd(residuals, cotangents)`` — optional custom backward returning
    one grad per input (arrays or IndexedSlices); when given, ``fwd``
    must return ``(outputs, residuals)``. Without ``bwd``, jax.vjp of
    ``fwd`` provides the gradient (the common case).
    """

    def __init__(self, name: str, fwd: Callable,
                 bwd: Optional[Callable] = None):
        self.name = name
        self.fwd = fwd
        self.bwd = bwd

    def __call__(self, *inputs, **attrs):
        from ..autograd.engine import apply, apply_custom_vjp
        from ..core.tensor import Tensor, to_tensor
        tin = tuple(i if isinstance(i, Tensor) or not _tensorable(i)
                    else to_tensor(i) for i in inputs)
        if self.bwd is None:
            return apply(self.name, self.fwd, tin, **attrs)
        return apply_custom_vjp(self.name, self.fwd, self.bwd, tin, **attrs)

    def __repr__(self):
        return f"CustomOp({self.name!r}, custom_bwd={self.bwd is not None})"


def _tensorable(x) -> bool:
    import jax
    return isinstance(x, (np.ndarray, jax.Array, list, tuple, int, float))


def register_op(name: str, fwd: Optional[Callable] = None,
                bwd: Optional[Callable] = None):
    """Register a custom op (reference custom_operator.cc
    RegisterOperatorWithMetaInfo). Usable directly or as a decorator::

        @register_op("my_gelu")
        def my_gelu(x):
            return x * 0.5 * (1 + jnp.tanh(0.79788456 * (x + 0.044715*x**3)))

        y = paddle.utils.get_op("my_gelu")(tensor)   # eager or traced
    """
    if fwd is None:
        def deco(fn):
            register_op(name, fn, bwd)
            return fn
        return deco
    if name in _REGISTRY:
        raise InvalidArgumentError(
            f"custom op {name!r} is already registered (the reference "
            f"rejects duplicate operator types the same way)")
    op = CustomOp(name, fwd, bwd)
    _REGISTRY[name] = op
    return op


def get_op(name: str) -> CustomOp:
    if name not in _REGISTRY:
        raise InvalidArgumentError(
            f"custom op {name!r} not registered; known: "
            f"{sorted(_REGISTRY)}")
    return _REGISTRY[name]


def registered_ops():
    return sorted(_REGISTRY)


def load_op_library(so_path: str, name: str, symbol: str,
                    out_like: int = 0):
    """Bridge a host C kernel into the op dispatch (reference
    LoadOpMetaInfoAndRegisterOp for .so custom ops).

    The C symbol must have signature
    ``void f(const float* in, float* out, int64_t n)`` (elementwise,
    f32). It runs on the HOST via ``jax.pure_callback`` — under jit XLA
    transfers the operand, calls back, and transfers the result; eagerly
    it is a plain call. ``out_like`` names which input supplies the
    output shape/dtype. TPU-resident custom kernels should be written as
    Pallas and registered with :func:`register_op` instead.
    """
    import jax
    import jax.numpy as jnp

    lib = ctypes.CDLL(so_path)
    cfn = getattr(lib, symbol)
    cfn.restype = None
    cfn.argtypes = [ctypes.POINTER(ctypes.c_float),
                    ctypes.POINTER(ctypes.c_float), ctypes.c_int64]

    def host_call(x):
        x = np.ascontiguousarray(np.asarray(x), np.float32)
        out = np.empty_like(x)
        cfn(x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            ctypes.c_int64(x.size))
        return out

    def fwd(*arrays):
        like = arrays[out_like]
        return jax.pure_callback(
            host_call, jax.ShapeDtypeStruct(like.shape, jnp.float32), like)

    return register_op(name, fwd)


class cpp_extension:
    """Namespace parity with ``paddle.utils.cpp_extension``: points users
    at the TPU-native custom-op route instead of CUDA build helpers."""

    @staticmethod
    def load(name=None, sources=None, **kwargs):
        raise InvalidArgumentError(
            "cpp_extension.load builds CUDA/C++ device ops, which cannot "
            "target TPU. Write the kernel as jax/Pallas and register it "
            "with paddle1_tpu.utils.register_op, or bridge a HOST C "
            "kernel with paddle1_tpu.utils.load_op_library.")

    CppExtension = staticmethod(load)
    CUDAExtension = staticmethod(load)
