"""ONNX export shim (reference python/paddle/onnx/export.py delegates to
the external paddle2onnx package). The TPU-native deployment format is the
serialized StableHLO program written by ``paddle1_tpu.jit.save`` — StableHLO
is the portable interchange here, playing ONNX's role. ``export`` therefore
saves the jit artifact and raises a clear error if a literal ``.onnx``
protobuf is demanded (no converter is bundled in this environment)."""

from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    if path.endswith(".onnx"):
        raise NotImplementedError(
            "Literal ONNX protobuf export requires an external converter "
            "(the reference shells out to paddle2onnx). Use "
            "paddle1_tpu.jit.save for the portable StableHLO artifact.")
    from ..jit import save as jit_save
    jit_save(layer, path, input_spec=input_spec)
    return path
