"""Crash flight recorder: the last N steps, spans and events, on disk
the moment something dies.

Post-mortems of training/serving crashes kept reconstructing "what was
the process doing right before?" from scattered logs. The flight
recorder keeps a bounded in-memory ring — the last N step metric
snapshots (fed by the engines' instrumented dispatch), recent completed
spans (tapped off :mod:`obs.trace`'s serializer, whether or not a span
sink is configured), and recent lifecycle events (tapped off
:mod:`obs.events`) — and dumps it ATOMICALLY as one JSONL bundle when:

* an uncaught exception unwinds (``sys.excepthook`` chain) — the
  ``bench --cost`` injected-crash gate;
* the process exits after a preemption/supervisor drain
  (``core.health.drain_requested()`` checked at ``atexit`` — the
  SIGTERM handler itself stays signal-safe: it only sets the flag it
  already sets);
* on demand — :meth:`FlightRecorder.dump` or the telemetry endpoint's
  ``GET /debug/flight`` route.

The bundle lands next to the trace sink (``obs_flight_dir`` flag, else
``obs_trace_dir``, else cwd) as ``flight-<pid>.jsonl``;
:func:`obs.trace.export_chrome_trace` merges ``flight-*.jsonl`` into
the chrome view (step snapshots and lifecycle events become instant
markers), so the final seconds before a crash render on the same
timeline as the healthy processes' spans.

Armed by ``obs_flight_steps = N`` (0, the default, is structurally
free: :func:`recorder` returns None and every tap site is a pointer
test). A SIGKILL still loses the ring — that is the one failure mode a
userspace recorder cannot cover; the trace sink's instant-flush
records are the SIGKILL story.
"""

from __future__ import annotations

import atexit
import collections
import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional

__all__ = ["FlightRecorder", "recorder", "reset", "read_bundle"]

# span/event rings are sized relative to the step ring: a step emits a
# handful of spans, and lifecycle events are rare
_SPAN_FACTOR = 8
_EVENT_RING = 256


class FlightRecorder:
    """The bounded ring + dump machinery. One per process (module
    singleton via :func:`recorder`); construct directly only in
    tests."""

    def __init__(self, steps: int, dir_hint: str = ""):
        self.capacity = int(steps)
        # the sink configured when the recorder was ARMED: a crash
        # after a flags_guard restored the flag must still dump where
        # the run asked, not into whatever cwd the process dies in
        self._dir_hint = dir_hint
        self._lock = threading.Lock()
        self._steps: collections.deque = collections.deque(
            maxlen=max(self.capacity, 1))           # guarded-by: self._lock
        self._spans: collections.deque = collections.deque(
            maxlen=max(self.capacity * _SPAN_FACTOR, 64))  # guarded-by: self._lock
        self._events: collections.deque = collections.deque(
            maxlen=_EVENT_RING)                     # guarded-by: self._lock
        self._dumped_reason: Optional[str] = None

    # -- feeds (hot-path: deque appends under a short lock) ----------------

    def note_step(self, **fields) -> None:
        rec = {"kind": "step", "ts": round(time.time(), 6)}
        rec.update(fields)
        with self._lock:
            self._steps.append(rec)

    def note_event(self, rec: dict) -> None:
        with self._lock:
            self._events.append(dict(rec, kind="event"))

    def note_span_line(self, line: str) -> None:
        """Raw serialized span JSONL line from the trace module —
        stored verbatim (it is already one bundle row)."""
        with self._lock:
            self._spans.append(line)

    # -- dump --------------------------------------------------------------

    def _rows(self, reason: str) -> List[str]:
        with self._lock:
            steps = list(self._steps)
            spans = list(self._spans)
            events = list(self._events)
        header = {"kind": "flight_header", "reason": reason,
                  "pid": os.getpid(), "ts": round(time.time(), 6),
                  "steps": len(steps), "spans": len(spans),
                  "events": len(events)}
        rows = [json.dumps(header, default=repr) + "\n"]
        for rec in steps + events:
            try:
                rows.append(json.dumps(rec, default=repr) + "\n")
            except (TypeError, ValueError):
                continue
        for line in spans:
            rows.append(line if line.endswith("\n") else line + "\n")
        return rows

    def dump_text(self, reason: str = "on_demand") -> str:
        return "".join(self._rows(reason))

    def dump_dir(self) -> str:
        from ..core import flags as core_flags
        return (core_flags.flag("obs_flight_dir")
                or core_flags.flag("obs_trace_dir")
                or self._dir_hint or os.getcwd())

    def dump(self, path: Optional[str] = None,
             reason: str = "on_demand", **extra) -> Optional[str]:
        """Write the bundle atomically (tmp + rename: a reader — or a
        second crash — never sees a torn file). Returns the path, or
        None when the write failed (a dying process must not die
        harder because its black box had no disk)."""
        return self.dump_bundle(path, reason, **extra)[0]

    def dump_bundle(self, path: Optional[str] = None,
                    reason: str = "on_demand", **extra):
        """One ring snapshot, written AND returned: ``(path, text)``.
        The /debug/flight route serves ``text`` so the on-disk bundle
        and the HTTP body are byte-identical (two snapshots could
        disagree by a step landing between them)."""
        if path is None:
            path = os.path.join(self.dump_dir(),
                                f"flight-{os.getpid()}.jsonl")
        rows = self._rows(reason)
        if extra:
            hdr = json.loads(rows[0])
            hdr.update(extra)
            rows[0] = json.dumps(hdr, default=repr) + "\n"
        text = "".join(rows)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(tmp, "w") as f:
                f.write(text)
            os.replace(tmp, path)
        except OSError:
            return None, text
        self._dumped_reason = reason
        return path, text


# -- the process recorder ---------------------------------------------------

_lock = threading.Lock()
_rec: Optional[FlightRecorder] = None
_hooks_installed = False
_prev_excepthook = None


def recorder() -> Optional[FlightRecorder]:
    """The armed process recorder, or None when ``obs_flight_steps``
    is 0 (one flag read — the structural-zero path). First armed call
    builds the ring, installs the crash hooks, and taps the trace +
    events streams."""
    from ..core import flags as core_flags
    n = int(core_flags.flag("obs_flight_steps"))
    if n <= 0:
        return None
    global _rec
    r = _rec
    if r is None or r.capacity != n:
        with _lock:
            if _rec is None or _rec.capacity != n:
                _rec = FlightRecorder(
                    n, dir_hint=(core_flags.flag("obs_flight_dir")
                                 or core_flags.flag("obs_trace_dir")))
                _install_hooks()
                _install_taps(_rec)
            r = _rec
    return r


def reset() -> None:
    """Drop the recorder + taps (test isolation). The excepthook/
    atexit chain stays installed (idempotent, checks arming)."""
    global _rec
    with _lock:
        _rec = None
    from . import trace as obs_trace
    from . import events as obs_events
    obs_trace.set_span_tap(None)
    obs_events.set_flight_tap(None)


def _install_taps(r: FlightRecorder) -> None:
    from . import trace as obs_trace
    from . import events as obs_events
    obs_trace.set_span_tap(r.note_span_line)
    obs_events.set_flight_tap(r.note_event)


def _install_hooks() -> None:
    # caller holds _lock
    global _hooks_installed, _prev_excepthook
    if _hooks_installed:
        return
    _hooks_installed = True
    _prev_excepthook = sys.excepthook

    def hook(exc_type, exc, tb):
        r = _rec
        if r is not None:
            r.dump(reason="crash",
                   error=f"{exc_type.__name__}: {exc}")
        (_prev_excepthook or sys.__excepthook__)(exc_type, exc, tb)

    sys.excepthook = hook

    def on_exit():
        r = _rec
        if r is None or r._dumped_reason == "crash":
            return
        try:
            from ..core import health
            if health.drain_requested():
                # a preemption / supervisor SIGTERM drain: the signal
                # handler only set a flag (signal-safe contract); the
                # bundle writes here, on the way out
                r.dump(reason="preemption")
        except Exception:  # noqa: broad-except — the black box must
            # never turn a clean exit into a dirty one
            pass

    atexit.register(on_exit)


def read_bundle(path: str) -> List[dict]:
    """Parse a flight bundle back (tests/tools), skipping torn lines."""
    out: List[dict] = []
    try:
        with open(path) as f:
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    out.append(json.loads(ln))
                except ValueError:
                    continue
    except OSError:
        pass
    return out
