"""Live telemetry endpoint: stdlib-HTTP ``/metrics`` + ``/healthz``.

One daemon thread per process (flag ``obs_port``; 0 disables), zero
dependencies: ``GET /metrics`` returns the Prometheus text exposition
of the process registry plus any extra provider pages (the fleet's
per-version/per-replica groups, a Supervisor's merged worker
snapshots), ``GET /healthz`` returns a small JSON liveness document.
The handler thread never touches the hot path — a scrape costs the
scraped, not the server.

Explicit ``port=0`` in the constructor binds an ephemeral port (tests,
multi-process fleets on one host) — the bound port is on ``.port``.
The flag value 0 means *disabled*; pick a real port (or -1 for
ephemeral) to serve.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Iterable, Optional

__all__ = ["TelemetryServer", "start_telemetry_from_flags"]


class TelemetryServer:
    """Serve ``/metrics`` and ``/healthz`` from a daemon thread.

    Parameters
    ----------
    port : TCP port; 0 binds an ephemeral one (read ``.port``).
    registry : the :class:`~paddle1_tpu.obs.registry.MetricsRegistry`
        whose page leads /metrics; defaults to the process registry.
        Pass ``registry=False`` to serve providers only.
    providers : callables returning extra exposition text appended to
        the page (fleet groups, merged child snapshots...). A provider
        raising is reported as a comment line, never a dead endpoint.
    healthz : callable returning the ``/healthz`` JSON dict; default
        ``{"ok": true, "pid": ..., "uptime_s": ...}``. Either way the
        document gains SLO verdicts (``obs.slo``) when the process has
        objectives configured.

    A provider that raises is served from its LAST GOOD page with a
    staleness comment (a scrape racing ``drain()``/teardown gets
    yesterday's numbers labeled as such, never a dead page); only a
    provider that has never succeeded degrades to an error comment.
    ``GET /debug/flight`` returns the flight recorder's current bundle
    (and writes the on-demand dump) when ``obs_flight_steps`` arms it.
    """

    def __init__(self, port: int = 0, registry=None,
                 providers: Iterable[Callable[[], str]] = (),
                 healthz: Optional[Callable[[], dict]] = None,
                 host: str = "127.0.0.1"):
        self._registry = registry
        # [fn, last_good_text, last_good_monotonic] per provider — the
        # scrape-vs-drain stale cache (ISSUE 13 satellite)
        self._providers = [[p, None, 0.0] for p in providers]
        self._healthz = healthz
        self._started = time.monotonic()
        srv_self = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # a scrape is not console news
                pass

            def _send(self, code, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    self._send(200, srv_self._metrics_page().encode(),
                               "text/plain; version=0.0.4")
                elif path == "/healthz":
                    self._send(200,
                               json.dumps(srv_self._health()).encode(),
                               "application/json")
                elif path == "/debug/flight":
                    body, code = srv_self._flight_page()
                    self._send(code, body.encode(),
                               "application/jsonl")
                else:
                    self._send(404, b"not found\n", "text/plain")

        self._httpd = ThreadingHTTPServer((host, max(int(port), 0)),
                                          _Handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    # -- pages -------------------------------------------------------------

    def _metrics_page(self) -> str:
        parts = []
        reg = self._registry
        if reg is None:
            from .registry import process_registry
            reg = process_registry()
        if reg is not False:
            parts.append(reg.render_text())
        for slot in self._providers:
            try:
                text = slot[0]()
            except Exception as e:  # noqa: broad-except — one broken
                # provider (a replica scrape racing a deploy/drain)
                # must not kill the whole page
                if slot[1] is not None:
                    # serve the last good page, labeled stale: a
                    # scrape racing drain()/teardown reads yesterday's
                    # numbers, never a provider-error hole
                    age = time.monotonic() - slot[2]
                    parts.append(slot[1])
                    parts.append(
                        f"# provider stale ({age:.1f}s old): {e!r}\n")
                else:
                    parts.append(f"# provider error: {e!r}\n")
                continue
            slot[1], slot[2] = text, time.monotonic()
            parts.append(text)
        return "".join(parts)

    def _health(self) -> dict:
        if self._healthz is not None:
            try:
                base = dict(self._healthz())
            except Exception as e:  # noqa: broad-except — a liveness
                # probe must answer even when the probed is sick
                base = {"ok": False, "error": repr(e),
                        "pid": os.getpid()}
        else:
            base = {"ok": True, "pid": os.getpid(),
                    "uptime_s": round(
                        time.monotonic() - self._started, 3)}
        try:
            from . import slo
            base.update(slo.healthz_fields(
                self._registry if self._registry not in (None, False)
                else None))
        except Exception as e:  # noqa: broad-except — a broken SLO
            # spec must degrade the verdict, not the liveness probe
            base["slo_error"] = repr(e)
        return base

    def _flight_page(self):
        from . import flight
        r = flight.recorder()
        if r is None:
            return ("flight recorder disarmed "
                    "(set FLAGS_obs_flight_steps > 0)\n", 404)
        # ONE ring snapshot: the disk dump and the HTTP body are the
        # same bytes (a step landing between two snapshots would make
        # the route disagree with the file)
        _path, text = r.dump_bundle(reason="debug_route")
        return (text, 200)

    # -- lifecycle ---------------------------------------------------------

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "TelemetryServer":
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                kwargs={"poll_interval": 0.2},
                daemon=True, name="p1t-obs-http")
            self._thread.start()
        return self

    def stop(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:  # pragma: no cover - teardown race
            pass
        if self._thread is not None:
            self._thread.join(timeout=5.0)


def resolve_port_flag(port: Optional[int]) -> Optional[int]:
    """THE ``obs_port`` semantics, shared by every start_telemetry
    surface: explicit ``port`` wins; None reads the flag; flag 0 means
    disabled (returns None); negative means ephemeral (bind port 0)."""
    if port is None:
        from ..core import flags as core_flags
        port = int(core_flags.flag("obs_port"))
        if port == 0:
            return None
    return 0 if port < 0 else int(port)


def start_telemetry_from_flags(providers: Iterable[Callable[[], str]] = (),
                               healthz: Optional[Callable[[], dict]] = None
                               ) -> Optional[TelemetryServer]:
    """Start the endpoint when the ``obs_port`` flag asks for one
    (0 = disabled, -1 = ephemeral, else the port). Returns the handle
    or None."""
    port = resolve_port_flag(None)
    if port is None:
        return None
    return TelemetryServer(port=port, providers=providers,
                           healthz=healthz).start()
