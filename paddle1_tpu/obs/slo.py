"""Declarative SLOs over the metrics registry: the autoscaler's sensor.

ROADMAP #4's controller needs one signal — "are we inside the service
objective, and how fast are we burning the budget?" — not a wall of
histograms. This module turns existing registry families into that
signal without new instrumentation:

* :class:`SloSpec` declares one objective over families that already
  exist — a **latency quantile** (``p99(e2e_ms) < 50``), an **error
  rate** (``errors_total / requests_total < 0.01``), or a
  **staleness** bound on a gauge (``age_seconds < 60``);
* :meth:`SloSet.evaluate` reads the registry (peek-only — evaluating
  an SLO must never create empty families and break the
  structural-zero proof), publishes per-objective burn-rate gauges
  (``slo_<name>_burn_rate_ratio`` = observed/target; > 1 is out of
  budget) and verdict gauges (``slo_<name>_ok``), and returns the
  verdict dict;
* :func:`healthz_fields` folds the verdicts into the ``/healthz``
  document — the endpoint the fleet Supervisor (and eventually the
  autoscaler) already polls.

Specs come from Python or from the ``obs_slos`` flag, a compact
grammar parsed with teaching errors::

    FLAGS_obs_slos="lat=p99(e2e_ms)<50;fresh=stale(model_age_seconds)<600"
    FLAGS_obs_slos="err=rate(errors_total/requests_total)<0.01"

Evaluation is pull-driven (a /healthz or /metrics scrape, a bench
assert, a controller tick) — the hot path never pays for it.
"""

from __future__ import annotations

import re
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from ..core.errors import InvalidArgumentError

__all__ = ["SloSpec", "SloSet", "parse_slos", "process_slos",
           "healthz_fields"]

_QUANTS = {"p50": 50.0, "p95": 95.0, "p99": 99.0}


@dataclass(frozen=True)
class SloSpec:
    """One objective. ``kind`` selects the read:

    * ``latency_quantile`` — ``hist`` family's ``quantile`` (50/95/99)
      must stay below ``target`` (same unit as the histogram);
    * ``error_rate`` — ``num``/``den`` counter ratio below ``target``;
    * ``staleness`` — ``gauge`` family's value below ``target``.
    """

    name: str
    kind: str
    target: float
    hist: Optional[str] = None
    quantile: float = 99.0
    num: Optional[str] = None
    den: Optional[str] = None
    gauge: Optional[str] = None

    def observe(self, registry) -> Optional[float]:
        """The observed value, or None when the families don't exist
        yet (no traffic = vacuously inside the objective)."""
        if self.kind == "latency_quantile":
            h = registry.peek(self.hist)
            if h is None or h[0] != "histogram" or not h[1].count:
                return None
            return float(h[1].percentile(self.quantile))
        if self.kind == "error_rate":
            num = registry.peek(self.num)
            den = registry.peek(self.den)
            if den is None or den[0] != "counter" or not den[1].value:
                return None
            n = num[1].value if (num is not None
                                 and num[0] == "counter") else 0
            return float(n) / float(den[1].value)
        if self.kind == "staleness":
            g = registry.peek(self.gauge)
            if g is None or g[0] != "gauge":
                return None
            return float(g[1].value)
        raise InvalidArgumentError(
            f"unknown SLO kind {self.kind!r} (latency_quantile / "
            "error_rate / staleness)")


class SloSet:
    """A bundle of objectives evaluated together (one service's SLO)."""

    def __init__(self, specs: Sequence[SloSpec]):
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise InvalidArgumentError(
                f"duplicate SLO names in {names} — each objective "
                "needs its own gauge family")
        self.specs: List[SloSpec] = list(specs)

    def evaluate(self, registry=None,
                 publish: bool = True) -> Dict[str, dict]:
        """Read every objective against ``registry`` (default: the
        process registry), publish burn-rate/verdict gauges, return
        ``{name: {ok, observed, target, burn_rate}}``. An objective
        whose families carry no data yet is ok with burn_rate 0 — no
        traffic can't be out of budget."""
        if registry is None:
            from .registry import process_registry
            registry = process_registry()
        out: Dict[str, dict] = {}
        for s in self.specs:
            obs_v = s.observe(registry)
            if obs_v is None:
                verdict = {"ok": True, "observed": None,
                           "target": s.target, "burn_rate": 0.0}
            else:
                burn = (obs_v / s.target) if s.target > 0 else (
                    0.0 if obs_v <= 0 else float("inf"))
                verdict = {"ok": obs_v < s.target,
                           "observed": round(obs_v, 6),
                           "target": s.target,
                           "burn_rate": round(burn, 4)}
            out[s.name] = verdict
            if publish:
                registry.gauge(
                    f"slo_{s.name}_burn_rate_ratio").set(
                        verdict["burn_rate"])
                registry.gauge(f"slo_{s.name}_ok").set(
                    1.0 if verdict["ok"] else 0.0)
        return out

    def ok(self, registry=None) -> bool:
        return all(v["ok"] for v in self.evaluate(registry).values())


# -- the flag grammar -------------------------------------------------------

_SPEC_RE = re.compile(
    r"^\s*(?P<name>[a-z][a-z0-9_]*)\s*=\s*"
    r"(?P<fn>p50|p95|p99|rate|stale)\s*\("
    r"(?P<args>[^)]*)\)\s*<\s*(?P<target>[0-9.eE+-]+)\s*$")

_GRAMMAR = ("'<name>=p99(<histogram>)<target>' | "
            "'<name>=rate(<errors_total>/<requests_total>)<target>' | "
            "'<name>=stale(<gauge>)<target>', ';'-separated")


def parse_slos(spec: str) -> SloSet:
    """Parse the ``obs_slos`` flag grammar into an :class:`SloSet`,
    naming the offending clause and the grammar on failure."""
    specs: List[SloSpec] = []
    for clause in str(spec).split(";"):
        if not clause.strip():
            continue
        m = _SPEC_RE.match(clause)
        if not m:
            raise InvalidArgumentError(
                f"bad SLO clause {clause.strip()!r} — grammar: "
                f"{_GRAMMAR}")
        name, fn = m.group("name"), m.group("fn")
        args = [a.strip() for a in m.group("args").split("/")]
        try:
            target = float(m.group("target"))
        except ValueError:
            raise InvalidArgumentError(
                f"bad SLO target in {clause.strip()!r}") from None
        if fn in _QUANTS:
            if len(args) != 1 or not args[0]:
                raise InvalidArgumentError(
                    f"{fn}() takes exactly one histogram family, got "
                    f"{args} in {clause.strip()!r}")
            specs.append(SloSpec(name, "latency_quantile", target,
                                 hist=args[0], quantile=_QUANTS[fn]))
        elif fn == "rate":
            if len(args) != 2 or not all(args):
                raise InvalidArgumentError(
                    "rate() takes numerator/denominator counter "
                    f"families, got {args} in {clause.strip()!r}")
            specs.append(SloSpec(name, "error_rate", target,
                                 num=args[0], den=args[1]))
        else:  # stale
            if len(args) != 1 or not args[0]:
                raise InvalidArgumentError(
                    "stale() takes exactly one gauge family, got "
                    f"{args} in {clause.strip()!r}")
            specs.append(SloSpec(name, "staleness", target,
                                 gauge=args[0]))
    return SloSet(specs)


# -- the process SLO set (what /healthz reports) ----------------------------

_lock = threading.Lock()
_process: Optional[SloSet] = None
_flag_cache = {"raw": None, "set": None}


def set_process_slos(slos: Optional[SloSet]) -> None:
    """Install (or clear) the process SLO set programmatically —
    overrides the ``obs_slos`` flag."""
    global _process
    with _lock:
        _process = slos


def process_slos() -> Optional[SloSet]:
    """The active process SLO set: the programmatic one, else the
    ``obs_slos`` flag parsed (cached per flag string), else None."""
    with _lock:
        if _process is not None:
            return _process
    from ..core import flags as core_flags
    raw = str(core_flags.flag("obs_slos"))
    if not raw.strip():
        return None
    with _lock:
        if _flag_cache["raw"] != raw:
            _flag_cache["raw"] = raw
            _flag_cache["set"] = parse_slos(raw)
        return _flag_cache["set"]


def healthz_fields(registry=None) -> Dict[str, object]:
    """The /healthz contribution: ``{}`` when no SLOs are configured,
    else ``{"slo_ok": bool, "slo": {name: verdict}}`` — the document
    ROADMAP #4's controller polls."""
    slos = process_slos()
    if slos is None:
        return {}
    verdicts = slos.evaluate(registry)
    return {"slo_ok": all(v["ok"] for v in verdicts.values()),
            "slo": verdicts}
