"""Unified metrics registry: counters + gauges + latency histograms.

Promoted from ``serving/metrics.py`` (ISSUE 10 tentpole): the serving
runtime's Counter/Gauge/Histogram grow a ``namespace`` and become the
single :class:`MetricsRegistry` every subsystem reports into —
``serving/metrics.py`` re-exports everything (zero API break, the
serving pages keep their ``p1t_serving_`` family prefix), while the
training side (engine step phases, checkpoint durations, loader
resilience, hapi fit) reports into the process-wide
:func:`process_registry` under the plain ``p1t_`` prefix.

Deliberately dependency-free and cheap: counters are a locked int,
gauges a plain float store, histograms keep exact count/sum plus a
bounded reservoir of recent observations for quantiles (latency
distributions are what the last few thousand observations say, not
what the process saw at boot). ``snapshot()`` returns a plain dict
(JSON-able; the test/bench surface and the cross-process aggregation
unit), ``render_text()`` emits Prometheus text exposition —
conformance locked by tests/test_obs.py's minimal parser: one
``# TYPE`` line per family per page, ``_total``-suffixed counters,
RAW (unrounded) monotone ``_sum``/``_count`` series so ``rate()``
works. ``tools/check_metric_names.py`` lints the metric-name contract
at the source level.

The fleet layer adds two multi-registry shapes on top:
:class:`MetricsGroup` keys child registries by a label (per model
version, per replica) so a rolling deploy's two versions never mix
their latencies, and :func:`merge_snapshots` folds many snapshots —
including ones shipped over the wire from replica subprocesses, or
read from Supervisor worker snapshot files — into one aggregate
(counters/count/sum add exactly; quantiles take the worst child, the
conservative merge for an SLO read). :func:`render_snapshot_text`
turns a merged snapshot back into a labeled exposition page for the
``/metrics`` endpoint.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

from ..core import locks
from ..core.errors import InvalidArgumentError

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "ServingMetrics", "MetricsGroup", "merge_snapshots",
           "render_snapshot_text", "process_registry",
           "reset_process_registry", "metrics_on", "step_registry",
           "SNAPSHOT_ENV", "write_snapshot_file"]

# reservoir size per histogram: large enough for a stable p99 (the
# quantile of the last ~4k observations), small enough to sort per
# snapshot without showing up in a profile
_RESERVOIR = 4096
# QPS window: rate over the last N responses' timestamps
_QPS_WINDOW = 512

# env var naming the JSON file a child process periodically publishes
# its process-registry snapshot to (atomic replace) — how a Supervisor
# aggregates training workers it cannot RPC into
SNAPSHOT_ENV = "PADDLE_OBS_SNAPSHOT"
_SNAPSHOT_INTERVAL_S = 1.0


class Counter:
    """Monotone counter (requests, sheds, compiles...)."""

    __slots__ = ("name", "_v", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> int:
        return self._v


class Gauge:
    """Last-written value (slot occupancy, queue depth...) — unlike a
    Counter it moves both ways; ``set`` is a plain float store (atomic
    under the GIL, no lock on the per-step hot path)."""

    __slots__ = ("name", "_v")

    def __init__(self, name: str):
        self.name = name
        self._v = 0.0

    def set(self, v: float) -> None:
        self._v = float(v)

    @property
    def value(self) -> float:
        return self._v


class Histogram:
    """Latency/occupancy histogram: exact count+sum, reservoir quantiles."""

    __slots__ = ("name", "_lock", "count", "sum", "max", "_recent")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self.count = 0
        self.sum = 0.0
        self.max = 0.0
        self._recent: collections.deque = collections.deque(
            maxlen=_RESERVOIR)

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            if v > self.max:
                self.max = v
            self._recent.append(v)

    def percentile(self, p: float) -> float:
        """Quantile over the reservoir (nearest-rank); 0.0 when empty."""
        with self._lock:
            data = sorted(self._recent)
        if not data:
            return 0.0
        idx = min(len(data) - 1, max(0, int(round(
            (p / 100.0) * (len(data) - 1)))))
        return data[idx]

    def totals(self) -> Tuple[int, float]:
        """Raw (count, sum) — unrounded, for the Prometheus ``_sum`` /
        ``_count`` series a ``rate()`` is computed from (the rounded
        ``summary()`` values drift a rate by up to 5e-5 per scrape)."""
        with self._lock:
            return self.count, self.sum

    def summary(self) -> Dict[str, float]:
        with self._lock:
            data = sorted(self._recent)
            count, total, mx = self.count, self.sum, self.max
        def q(p):
            if not data:
                return 0.0
            return data[min(len(data) - 1,
                            max(0, int(round((p / 100.0)
                                             * (len(data) - 1)))))]
        return {"count": count, "sum": round(total, 4),
                "mean": round(total / count, 4) if count else 0.0,
                "p50": round(q(50), 4), "p95": round(q(95), 4),
                "p99": round(q(99), 4), "max": round(mx, 4)}


def _fmt_line(name, value, pairs=(), label=None):
    """One exposition sample line (shared by the registry page and the
    merged-snapshot page — label quoting must never drift between
    them)."""
    pairs = [p for p in pairs if p is not None]
    if label is not None:
        pairs = pairs + [label]
    if pairs:
        lab = ",".join(f'{k}="{v}"' for k, v in pairs)
        return f"{name}{{{lab}}} {value}"
    return f"{name} {value}"


class MetricsRegistry:
    """One process's (or one Server's) registry. Counters, gauges and
    histograms are created on first touch, so instrumentation points
    never need registration boilerplate and ``snapshot()`` only reports
    what actually fired. A name registered as one kind can never be
    re-registered as another — the duplicate-family guard the
    exposition format depends on (one ``# TYPE`` per family)."""

    def __init__(self, namespace: str = "p1t_serving"):
        self.namespace = str(namespace)
        self._lock = locks.make_lock("MetricsRegistry._lock")
        self._counters: Dict[str, Counter] = {}      # guarded-by: self._lock
        self._gauges: Dict[str, Gauge] = {}          # guarded-by: self._lock
        self._histograms: Dict[str, Histogram] = {}  # guarded-by: self._lock
        # (family dicts are lock-free on the READ fast path by design —
        # `get` then locked setdefault — so only mutation is guarded)
        self._resp_times = collections.deque(maxlen=_QPS_WINDOW)  # guarded-by: self._lock
        self._started = time.monotonic()

    # -- instrumentation surface -------------------------------------------

    def _check_kind(self, name: str, kind: str) -> None:
        for other, table in (("counter", self._counters),
                             ("gauge", self._gauges),
                             ("histogram", self._histograms)):
            if other != kind and name in table:
                raise InvalidArgumentError(
                    f"metric family {name!r} is already registered as a "
                    f"{other} — one family, one kind (the exposition "
                    "format allows a single # TYPE per family)")

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                self._check_kind(name, "counter")
                c = self._counters.setdefault(name, Counter(name))
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                self._check_kind(name, "gauge")
                g = self._gauges.setdefault(name, Gauge(name))
        return g

    def histogram(self, name: str) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            with self._lock:
                self._check_kind(name, "histogram")
                h = self._histograms.setdefault(name, Histogram(name))
        return h

    def peek(self, name: str):
        """Look a family up WITHOUT creating it: ``(kind, obj)`` or
        None. The SLO evaluator reads through this — evaluating an
        objective over a family that never fired must not materialize
        an empty family (the structural-zero proof counts families)."""
        c = self._counters.get(name)
        if c is not None:
            return ("counter", c)
        g = self._gauges.get(name)
        if g is not None:
            return ("gauge", g)
        h = self._histograms.get(name)
        if h is not None:
            return ("histogram", h)
        return None

    def record_response(self, n: int = 1) -> None:
        """Feed the QPS window (called once per completed request)."""
        now = time.monotonic()
        with self._lock:
            for _ in range(n):
                self._resp_times.append(now)

    def qps(self) -> float:
        """Responses/second over the recent-response window."""
        with self._lock:
            if len(self._resp_times) < 2:
                return 0.0
            span = self._resp_times[-1] - self._resp_times[0]
            n = len(self._resp_times) - 1
        if span <= 0:
            # burst faster than the clock tick: rate over process life
            span = max(time.monotonic() - self._started, 1e-6)
            n += 1
        return n / span

    # -- export surface -----------------------------------------------------

    def empty(self) -> bool:
        """True when no metric family was ever touched (the bench
        --obs structural proof that disabled instrumentation did
        literally nothing)."""
        with self._lock:
            return not (self._counters or self._gauges
                        or self._histograms)

    def snapshot(self) -> Dict[str, object]:
        """The whole registry as one JSON-able dict."""
        with self._lock:
            counters = {n: c.value for n, c in self._counters.items()}
            gauges = {n: g.value for n, g in self._gauges.items()}
            hists = list(self._histograms.values())
        return {
            "qps": round(self.qps(), 2),
            "uptime_s": round(time.monotonic() - self._started, 3),
            "counters": counters,
            "gauges": gauges,
            "histograms": {h.name: h.summary() for h in hists},
        }

    def render_text(self, label: Optional[Tuple[str, str]] = None,
                    type_headers: bool = True) -> str:
        """Prometheus-style plain-text exposition (one scrape page).

        Histograms are emitted as Prometheus *summaries*: a ``# TYPE``
        header, quantile-labeled gauges, and RAW (unrounded) monotone
        ``_sum``/``_count`` series — the pair ``rate()`` needs, so
        ``rate(..._sum[1m]) / rate(..._count[1m])`` yields a true
        rolling mean (the rounded summary values would drift it).
        Counters and gauges get their own ``# TYPE`` lines. The legacy
        ``_mean``/``_max``/``_p50``/``_p95``/``_p99`` gauge lines are
        kept for existing scrapers. ``label`` tags every sample with
        one extra ``key="value"`` pair — the :class:`MetricsGroup`
        per-version/per-replica pages, which pass
        ``type_headers=False``: the text format allows one TYPE line
        per metric family per page, so a multi-child page emits the
        labeled samples untyped rather than a duplicate header per
        child (untyped samples parse fine; duplicate TYPE lines do
        not)."""
        def line(name, value, *pairs):
            return _fmt_line(name, value, pairs, label)

        ns = self.namespace
        with self._lock:
            counters = {n: c.value for n, c in self._counters.items()}
            gauges = {n: g.value for n, g in self._gauges.items()}
            hists = list(self._histograms.values())
        lines = [line(f"{ns}_qps", round(self.qps(), 2)),
                 line(f"{ns}_uptime_seconds",
                      round(time.monotonic() - self._started, 3))]
        for name, v in sorted(counters.items()):
            if type_headers:
                lines.append(f"# TYPE {ns}_{name} counter")
            lines.append(line(f"{ns}_{name}", v))
        for name, v in sorted(gauges.items()):
            if type_headers:
                lines.append(f"# TYPE {ns}_{name} gauge")
            lines.append(line(f"{ns}_{name}", v))
        for h in sorted(hists, key=lambda h: h.name):
            base = f"{ns}_{h.name}"
            s = h.summary()
            count, total = h.totals()
            if type_headers:
                lines.append(f"# TYPE {base} summary")
            for q, stat in (("0.5", "p50"), ("0.95", "p95"),
                            ("0.99", "p99")):
                lines.append(line(base, s[stat], ("quantile", q)))
            lines.append(line(base + "_sum", repr(float(total))))
            lines.append(line(base + "_count", count))
            for stat in ("mean", "p50", "p95", "p99", "max"):
                lines.append(line(f"{base}_{stat}", s[stat]))
        return "\n".join(lines) + "\n"


# serving's historical name for the class; per-Server registries keep
# the p1t_serving_ namespace (and their exposition pages) unchanged
ServingMetrics = MetricsRegistry


class MetricsGroup:
    """A labeled family of :class:`MetricsRegistry` children — the
    fleet's per-model-version and per-replica split (a rolling deploy
    serves two versions at once; mixing their latency histograms would
    hide a regression in the new one behind the old one's volume).
    Children are created on first touch, like the registry's own
    counters; :meth:`aggregate` folds them into one fleet-wide view."""

    def __init__(self, label_key: str, namespace: str = "p1t_serving"):
        self.label_key = label_key
        self.namespace = namespace
        self._lock = locks.make_lock("MetricsGroup._lock")
        self._children: Dict[str, MetricsRegistry] = {}  # guarded-by: self._lock

    def child(self, label) -> MetricsRegistry:
        label = str(label)
        m = self._children.get(label)
        if m is None:
            with self._lock:
                m = self._children.setdefault(
                    label, MetricsRegistry(namespace=self.namespace))
        return m

    def labels(self) -> List[str]:
        with self._lock:
            return sorted(self._children)

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        with self._lock:
            kids = dict(self._children)
        return {label: m.snapshot() for label, m in sorted(kids.items())}

    def aggregate(self) -> Dict[str, object]:
        return merge_snapshots(self.snapshot().values())

    def render_text(self) -> str:
        with self._lock:
            kids = dict(self._children)
        return "".join(
            m.render_text(label=(self.label_key, label),
                          type_headers=False)
            for label, m in sorted(kids.items()))


def merge_snapshots(snaps: Iterable[Dict[str, object]]
                    ) -> Dict[str, object]:
    """Fold many ``MetricsRegistry.snapshot()`` dicts into one aggregate
    (across a MetricsGroup's children, across replica subprocesses'
    wire-shipped snapshots, or across Supervisor workers' snapshot
    files). Counters, histogram counts and sums add exactly;
    quantiles/max take the WORST child — reservoir quantiles cannot be
    merged without the raw observations, and for an SLO read the
    conservative bound is the useful one (documented on the line a
    dashboard reads: an aggregate p99 here is "no child was worse")."""
    counters: Dict[str, int] = {}
    gauges: Dict[str, float] = {}
    hists: Dict[str, Dict[str, float]] = {}
    qps = 0.0
    uptime = 0.0
    for s in snaps:
        qps += float(s.get("qps", 0.0) or 0.0)
        uptime = max(uptime, float(s.get("uptime_s", 0.0) or 0.0))
        for k, v in (s.get("counters") or {}).items():
            counters[k] = counters.get(k, 0) + v
        for k, v in (s.get("gauges") or {}).items():
            # gauges are instantaneous levels, not totals: like the
            # quantiles, the aggregate takes the WORST (highest) child
            gauges[k] = max(gauges.get(k, 0.0), float(v))
        for name, h in (s.get("histograms") or {}).items():
            m = hists.setdefault(name, {
                "count": 0, "sum": 0.0, "mean": 0.0, "p50": 0.0,
                "p95": 0.0, "p99": 0.0, "max": 0.0})
            m["count"] += h["count"]
            m["sum"] += h["sum"]
            for q in ("p50", "p95", "p99", "max"):
                m[q] = max(m[q], h[q])
    for m in hists.values():
        m["mean"] = (round(m["sum"] / m["count"], 4) if m["count"]
                     else 0.0)
        m["sum"] = round(m["sum"], 4)
    return {"qps": round(qps, 2), "uptime_s": uptime,
            "counters": counters, "gauges": gauges,
            "histograms": hists}


def render_snapshot_text(snap: Dict[str, object], namespace: str,
                         label: Optional[Tuple[str, str]] = None) -> str:
    """Render a snapshot dict (typically a :func:`merge_snapshots`
    aggregate) as a labeled, UNTYPED exposition page — the merged-page
    analog of ``MetricsGroup.render_text`` for the ``/metrics``
    endpoint. Untyped because the same families may already carry a
    ``# TYPE`` on the live page above; merged histogram sums are the
    rounded aggregate values, so a rate() should be computed from the
    children's raw pages, not from here."""
    def line(name, value, *pairs):
        return _fmt_line(name, value, pairs, label)

    lines = [line(f"{namespace}_qps", snap.get("qps", 0.0)),
             line(f"{namespace}_uptime_seconds",
                  snap.get("uptime_s", 0.0))]
    for name, v in sorted((snap.get("counters") or {}).items()):
        lines.append(line(f"{namespace}_{name}", v))
    for name, v in sorted((snap.get("gauges") or {}).items()):
        lines.append(line(f"{namespace}_{name}", v))
    for name, h in sorted((snap.get("histograms") or {}).items()):
        base = f"{namespace}_{name}"
        for q, stat in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
            lines.append(line(base, h.get(stat, 0.0), ("quantile", q)))
        lines.append(line(base + "_sum", h.get("sum", 0.0)))
        lines.append(line(base + "_count", h.get("count", 0)))
        lines.append(line(base + "_max", h.get("max", 0.0)))
    return "\n".join(lines) + "\n"


# -- the process-wide registry ---------------------------------------------

_process_lock = threading.Lock()
_process: Optional[MetricsRegistry] = None
_snapshot_thread: Optional[threading.Thread] = None


def process_registry() -> MetricsRegistry:
    """THE process registry (namespace ``p1t``) every non-serving
    subsystem reports into — created on first touch. If the
    environment carries ``PADDLE_OBS_SNAPSHOT`` (a Supervisor set it
    for this worker), a daemon thread starts publishing the registry's
    snapshot there every second so the parent's ``/metrics`` page can
    aggregate children it cannot RPC into."""
    global _process
    m = _process
    if m is None:
        with _process_lock:
            if _process is None:
                _process = MetricsRegistry(namespace="p1t")
                _maybe_start_snapshot_writer()
            m = _process
    return m


def reset_process_registry() -> MetricsRegistry:
    """Replace the process registry with a fresh one (test isolation).
    Arms the snapshot writer like first touch does — a worker that
    resets before ever touching the registry must still publish."""
    global _process
    with _process_lock:
        _process = MetricsRegistry(namespace="p1t")
        _maybe_start_snapshot_writer()
        return _process


def metrics_on() -> bool:
    """Whether per-step (hot-path) training instrumentation is enabled
    — the ``obs_metrics`` flag. Cold-path lifecycle counters record
    regardless; this gate exists so the disabled per-step cost is ≈ 0
    (the bench --obs contract)."""
    from ..core import flags as core_flags
    return bool(core_flags.flag("obs_metrics"))


def step_registry() -> Optional[MetricsRegistry]:
    """The process registry when ``obs_metrics`` is on, else None —
    the one-call guard hot paths use (``m = step_registry()`` then
    ``if m is not None: ...``)."""
    return process_registry() if metrics_on() else None


def write_snapshot_file(path: str,
                        registry: Optional[MetricsRegistry] = None
                        ) -> None:
    """Atomically publish one registry snapshot as JSON (tmp+rename so
    a reader never sees a torn file)."""
    reg = registry if registry is not None else process_registry()
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(reg.snapshot(), f)
    os.replace(tmp, path)


def _maybe_start_snapshot_writer() -> None:
    # caller holds _process_lock
    global _snapshot_thread
    path = os.environ.get(SNAPSHOT_ENV)
    if not path or _snapshot_thread is not None:
        return

    def loop():
        import warnings
        warned = False
        while True:
            time.sleep(_SNAPSHOT_INTERVAL_S)
            try:
                write_snapshot_file(path)
            except OSError as e:
                if not warned:  # once — telemetry must never kill work
                    warned = True
                    warnings.warn(
                        f"obs snapshot file {path!r} not writable: {e}")

    _snapshot_thread = threading.Thread(target=loop, daemon=True,
                                        name="p1t-obs-snapshot")
    _snapshot_thread.start()
