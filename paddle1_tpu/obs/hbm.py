"""Live HBM accounting: a buffer census tagged by subsystem.

ROADMAP #2's out-of-HBM embedding tables and #3's "tokens/s per HBM
byte" have no sensor to optimize against: the repo could see *when*
memory died (the XLA OOM) but never *who* held it. The reference keeps
allocator stat counters in its L1 memory manager
(``memory/allocation``); the TPU-native analog can't intercept the
allocator (XLA/PJRT owns it), so the census works from the other end —
the subsystems that OWN device state register their live trees:

* engines call :func:`register` with a weakly-referenced owner and a
  getter (``params`` / ``opt_state`` / ``kv_cache`` / ``activations``
  / ``other``); registration is a list append, touches no registry,
  and dies with the owner (weakref — a census must never keep an
  engine alive);
* :func:`census` sums ``nbytes`` over every live provider's tree and
  compares against what the device itself reports
  (``device.memory_stats()`` where the backend has it, else the
  ``jax.live_arrays()`` walk) — the ``bench --cost`` gate holds the
  census to >= 95% of device-reported live bytes, i.e. "every big
  consumer is tagged";
* :func:`publish` writes the per-subsystem ``hbm_<subsystem>_bytes``
  gauges (hot-path form: registered trees only, no live_arrays walk);
  the full census adds the device watermark gauges
  (``hbm_device_bytes_in_use`` / ``hbm_device_peak_bytes`` /
  ``hbm_census_coverage_ratio``).

The **growth detector** (flag ``obs_hbm_leak_steps = K``, off by
default) watches the per-step census total and raises a typed,
teaching :class:`HbmLeakSuspected` after K consecutive
strictly-monotone growth steps — the debug-sanitizer idiom
(``core/locks.py`` / ``core/jit_sanitizer.py``): structurally free
when off, deterministic and loud when armed.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Tuple

from ..core.errors import EnforceNotMet

__all__ = ["SUBSYSTEMS", "HbmLeakSuspected", "register", "unregister",
           "census", "publish", "device_live_bytes", "reset",
           "leak_note", "step_sample"]

# the attribution buckets (ISSUE 13; "embed" added for ISSUE 19's
# sharded embedding engine — its LOGICAL HBM occupancy, distinct from
# the fixed weight allocation that stays under "params"): anything
# registered outside the named buckets lands in "other" so the
# coverage ratio stays honest
SUBSYSTEMS = ("params", "opt_state", "kv_cache", "activations", "embed",
              "other")


class HbmLeakSuspected(EnforceNotMet):
    """Raised (only when ``obs_hbm_leak_steps`` > 0) after K
    consecutive steps of strictly growing registered-buffer bytes."""


_lock = threading.Lock()
# (subsystem, name, weakref(owner), getter(owner) -> tree)
_providers: List[Tuple[str, str, "weakref.ref", Callable]] = []  # guarded-by: _lock

# leak-detector state: (last_bytes, consecutive_growth_steps)
_leak = {"last": None, "growth": 0}


def register(subsystem: str, owner, getter: Callable,
             name: Optional[str] = None) -> None:
    """Tag ``getter(owner)``'s tree as ``subsystem`` bytes. ``owner``
    is held by weakref — when it dies the registration evaporates —
    and ``getter`` must not close over device arrays itself (reach
    them THROUGH ``owner``), or the closure would pin what the weakref
    promises to release. Unknown subsystems fold into "other" (census
    coverage over precision). Each register prunes dead entries, so a
    process that constructs engines in a loop with observability off
    (census never walks) still keeps the provider list bounded."""
    sub = subsystem if subsystem in SUBSYSTEMS else "other"
    ref = weakref.ref(owner)
    with _lock:
        _providers[:] = [p for p in _providers if p[2]() is not None]
        _providers.append((sub, name or type(owner).__name__, ref,
                           getter))


def unregister(owner) -> None:
    """Drop every registration owned by ``owner`` (engine teardown)."""
    with _lock:
        _providers[:] = [p for p in _providers
                         if p[2]() is not None and p[2]() is not owner]


def reset() -> None:
    """Clear all registrations + leak/sampling state (test isolation)."""
    with _lock:
        _providers.clear()
    _leak["last"], _leak["growth"] = None, 0
    _sample["t"], _sample["total"] = 0.0, 0


def _live_providers():
    out = []
    dead = False
    with _lock:
        snap = list(_providers)
    for sub, name, ref, getter in snap:
        owner = ref()
        if owner is None:
            dead = True
            continue
        out.append((sub, name, owner, getter))
    if dead:
        with _lock:
            _providers[:] = [p for p in _providers if p[2]() is not None]
    return out


def registered_bytes() -> Dict[str, int]:
    """Per-subsystem byte totals over live registrations (the cheap,
    hot-path-safe half of the census: no live_arrays walk). A buffer
    reachable from two providers — the Layer's master copy aliasing
    the engine's params after a donate=False ``sync_model`` — counts
    ONCE (first registration wins): the census answers "who holds how
    many bytes", and double-counting an alias would push coverage past
    1.0 and hide untagged consumers."""
    import jax
    out = {s: 0 for s in SUBSYSTEMS}
    seen: set = set()
    for sub, _name, owner, getter in _live_providers():
        try:
            for leaf in jax.tree_util.tree_leaves(getter(owner)):
                nb = int(getattr(leaf, "nbytes", 0) or 0)
                if not nb:
                    continue
                key = id(leaf)
                if key in seen:
                    continue
                seen.add(key)
                out[sub] += nb
        except Exception:  # noqa: broad-except — a provider mid-
            # teardown (engine being deleted under a scrape) must cost
            # 0 bytes, never kill the census
            continue
    return out


def device_live_bytes() -> Tuple[int, str]:
    """What the device itself says is alive: ``memory_stats()`` where
    the backend reports it (TPU), else the ``jax.live_arrays()`` sum
    (CPU/tests). Returns (bytes, source)."""
    import jax
    try:
        stats = jax.devices()[0].memory_stats()
    except Exception:  # noqa: broad-except — an exotic backend without
        # the PJRT stats API must fall through to the live-array walk
        stats = None
    if stats and stats.get("bytes_in_use"):
        return int(stats["bytes_in_use"]), "memory_stats"
    return (sum(int(a.nbytes) for a in jax.live_arrays()),
            "live_arrays")


def census() -> Dict[str, object]:
    """The full picture: per-subsystem registered bytes, the device's
    own number, and the coverage ratio the acceptance gate asserts
    (>= 0.95 = every big consumer is tagged)."""
    per = registered_bytes()
    total = _physical_total(per)
    dev, source = device_live_bytes()
    peak = 0
    try:
        import jax
        stats = jax.devices()[0].memory_stats()
        if stats:
            peak = int(stats.get("peak_bytes_in_use", 0) or 0)
    except Exception:  # noqa: broad-except — watermark is optional
        pass
    return {"subsystems": per, "census_bytes": total,
            "device_bytes_in_use": dev, "device_source": source,
            "device_peak_bytes": peak,
            "coverage_ratio": (total / dev) if dev else 1.0}


def _physical_total(per: Dict[str, int]) -> int:
    """Sum of the buckets that correspond to real device allocations.
    "embed" is a LOGICAL view (resident embedding rows; the backing
    weight allocation is already counted under "params"), so it is
    excluded from totals/coverage — counting it twice would push
    coverage past 1.0 and hide untagged consumers."""
    return sum(b for s, b in per.items() if s != "embed")


def publish(m, full: bool = False) -> int:
    """Write the census gauges into registry ``m``. The default form
    is the hot-path one (registered trees only); ``full=True`` adds
    the device watermark + coverage gauges (scrape/bench cadence — the
    ``live_arrays`` walk is not a per-step cost). Returns the
    registered total (the leak detector's input)."""
    if full:
        c = census()
        per, total = c["subsystems"], c["census_bytes"]
        m.gauge("hbm_device_bytes_in_use").set(c["device_bytes_in_use"])
        if c["device_peak_bytes"]:
            m.gauge("hbm_device_peak_bytes").set(c["device_peak_bytes"])
        m.gauge("hbm_census_coverage_ratio").set(c["coverage_ratio"])
    else:
        per = registered_bytes()
        total = _physical_total(per)
    for sub, b in per.items():
        if b:
            m.gauge(f"hbm_{sub}_bytes").set(b)
    m.gauge("hbm_census_bytes").set(total)
    return total


# hot-path sampling: a full registered-tree walk is O(leaves) — fine
# on demand, too hot per step next to a big engine (a live BERT is
# ~800 leaves). The step path samples at most every interval; buffer
# sizes only change when allocations change, so the sampled series
# sees every leak the per-step series would.
_SAMPLE_INTERVAL_S = 0.25
_sample = {"t": 0.0, "total": 0}


def last_total() -> int:
    """The most recent sampled census total (free; 0 before the first
    sample)."""
    return _sample["total"]


def step_sample(m) -> int:
    """The per-step census feed: publish + leak-detect at most once
    per ``_SAMPLE_INTERVAL_S`` (the engines call this from the
    instrumented dispatch); between samples it returns the last total
    for free. The growth detector therefore counts monotone-growth
    SAMPLES, not raw steps."""
    now = time.monotonic()
    if now - _sample["t"] < _SAMPLE_INTERVAL_S:
        return _sample["total"]
    _sample["t"] = now
    _sample["total"] = publish(m)
    leak_note(_sample["total"])
    return _sample["total"]


def leak_note(total_bytes: int) -> None:
    """Feed the growth detector one step's census total. Armed by
    ``obs_hbm_leak_steps`` (K > 0): K consecutive strictly-growing
    steps raise :class:`HbmLeakSuspected`. Off (0, the default) this
    is one flag read."""
    from ..core import flags as core_flags
    k = int(core_flags.flag("obs_hbm_leak_steps"))
    if k <= 0:
        _leak["last"], _leak["growth"] = None, 0
        return
    last = _leak["last"]
    _leak["last"] = total_bytes
    if last is None:
        return
    if total_bytes > last:
        _leak["growth"] += 1
    else:
        _leak["growth"] = 0
        return
    if _leak["growth"] >= k:
        growth = _leak["growth"]
        _leak["last"], _leak["growth"] = None, 0
        raise HbmLeakSuspected(
            f"registered device bytes grew for {growth} consecutive "
            f"steps (now {total_bytes:,} bytes) — a steady-state "
            "training/serving step should re-donate its buffers, not "
            "accumulate them. Usual suspects: a list keeping every "
            "step's LossFuture alive (read or drop them), donation "
            "disabled (jit_donate_params=0) while something retains "
            "old param trees, or an activations/other provider that "
            "grows per step. obs.hbm.census() attributes the bytes "
            "per subsystem; set FLAGS_obs_hbm_leak_steps=0 to disarm "
            "this detector.")
