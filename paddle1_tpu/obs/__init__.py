"""Process-wide observability: one pane of glass (ISSUE 10).

Three layers, each opt-in and independently cheap:

* **Metrics** — :mod:`obs.registry` promotes the serving runtime's
  Counter/Gauge/Histogram into the single process-wide
  :class:`MetricsRegistry`. Serving keeps its per-Server registries
  (re-exported from ``serving/metrics.py``, zero API break); training
  (:class:`~paddle1_tpu.distributed.ParallelEngine` step phases,
  :class:`~paddle1_tpu.distributed.ResilientTrainer` checkpoints,
  loader resilience, :class:`~paddle1_tpu.hapi.callbacks.
  MetricsCallback`) reports into :func:`process_registry`. Per-step
  phase timing is gated by the ``obs_metrics`` flag so the disabled
  cost is ≈ 0 (the ``bench.py --obs`` gate); rare lifecycle counters
  (checkpoints, restarts, quarantines) are always on.
* **Tracing** — :mod:`obs.trace` extends profiler spans with
  trace_id/span_id context that crosses process boundaries: over the
  serving wire protocol's frame header, and into Supervisor worker env
  via ``PADDLE_OBS_TRACE_CTX``. With ``obs_trace_dir`` set, every
  process appends completed spans to ``spans-<pid>.jsonl`` there and
  :func:`~paddle1_tpu.obs.trace.export_chrome_trace` merges them into
  ONE chrome://tracing view with flow arrows — a request flowing
  client → fleet router → replica → batcher → dispatch, or a training
  step's host-side phase breakdown.
* **Live telemetry** — :mod:`obs.http` serves ``/metrics`` (Prometheus
  text exposition) and ``/healthz`` from a stdlib daemon thread (flag
  ``obs_port``); ``ServingFleet.start_telemetry`` and
  ``Supervisor.start_telemetry`` aggregate child pages via
  :func:`merge_snapshots`. :mod:`obs.events` is the structured JSONL
  lifecycle journal (restart, resize, deploy, shed, quarantine,
  checkpoint commit) behind ``obs_events_file``.

The cost observatory (ISSUE 13) adds what things *cost*:
:mod:`obs.costmodel` derives per-executable FLOPs/bytes from XLA's
cost analysis (``train_mfu`` / ``train_hbm_bw_util`` gauges, the
``hapi.summary`` FLOPs column), :mod:`obs.hbm` is the live-buffer
census by subsystem plus the flag-gated monotone-growth leak detector,
:mod:`obs.slo` evaluates declarative SLOs (burn-rate gauges +
``/healthz`` verdicts — ROADMAP #4's sensor), and :mod:`obs.flight` is
the crash flight recorder (bounded ring of recent steps/spans/events,
dumped atomically on crash/preemption/``GET /debug/flight``, merged by
``export_chrome_trace``). All of it rides the same discipline:
structurally zero when off, < 5% enabled (``bench.py --cost``).
"""

from __future__ import annotations

from . import costmodel, events, flight, hbm, slo, trace
from .costmodel import ExecutableCost
from .flight import FlightRecorder
from .hbm import HbmLeakSuspected
from .http import TelemetryServer, start_telemetry_from_flags
from .registry import (Counter, Gauge, Histogram, MetricsGroup,
                       MetricsRegistry, ServingMetrics, merge_snapshots,
                       metrics_on, process_registry, render_snapshot_text,
                       reset_process_registry, step_registry)
from .slo import SloSet, SloSpec, parse_slos

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "ServingMetrics",
    "MetricsGroup", "merge_snapshots", "render_snapshot_text",
    "process_registry", "reset_process_registry", "metrics_on",
    "step_registry", "TelemetryServer", "start_telemetry_from_flags",
    "trace", "events", "costmodel", "hbm", "slo", "flight",
    "ExecutableCost", "FlightRecorder", "HbmLeakSuspected",
    "SloSet", "SloSpec", "parse_slos",
]
