"""Cross-process request/step tracing (ISSUE 10 tentpole, part 2).

The profiler's host spans know how to time one thread of one process;
this module gives them identity that SURVIVES process boundaries:

* a **trace id** names one logical flow — a serving request crossing
  client → fleet router → replica → batcher → dispatch, or one
  supervised training job;
* a **span id** names one timed operation inside it; spans carry their
  parent span id, which is how the chrome-trace exporter draws flow
  arrows between processes.

Context travels three ways:

* **thread-local stack** — :func:`context` / :class:`span` push the
  current (trace_id, span_id) so nested spans parent correctly;
* **wire header** — :func:`wire_header` / :func:`adopt_header` put the
  context into (and read it from) the serving wire protocol's JSON
  frame header (``serving/wire.py``);
* **worker env** — ``PADDLE_OBS_TRACE_CTX=<trace>:<span>`` seeds a
  spawned worker's process-default context (the Supervisor stamps it),
  so a training worker's step spans join the job's trace.

With the ``obs_trace_dir`` flag set, every completed span (and every
:func:`instant` marker) is appended — one JSON line, flushed — to
``<dir>/spans-<pid>.jsonl``. Timestamps are epoch microseconds
(``time.time``), the one clock processes on a host share;
:func:`export_chrome_trace` merges every ``spans-*.jsonl`` into one
chrome://tracing JSON with flow events linking parent → child spans
across pids. A SIGKILLed process keeps everything it already flushed —
which is exactly what makes a wedged replica visible in the trace.

When nothing is enabled every entry point is a flag read and an early
return; :class:`span` hands back a shared no-op context manager, so
instrumented hot paths cost ≈ 0 disabled (the bench --obs gate).
"""

from __future__ import annotations

import atexit
import contextlib
import itertools
import json
import os
import re
import threading
import time
import uuid
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ..core import flags as core_flags

__all__ = ["TRACE_CTX_ENV", "sink_active", "new_trace_id", "new_span_id",
           "current", "context", "span", "instant", "record_span",
           "wire_header", "adopt_header", "set_process_context",
           "process_context", "export_chrome_trace", "set_span_tap"]

TRACE_CTX_ENV = "PADDLE_OBS_TRACE_CTX"

_tls = threading.local()
_lock = threading.Lock()
_file = None          # (pid, fh) — reopened after fork
_proc_ctx: Optional[Tuple[str, str]] = None
_warned = False


# ids (ours, or adopted from wire headers / env) must stay inside this
# alphabet: the hot-path serializer interpolates them unescaped
_ID_RE = re.compile(r"^[A-Za-z0-9_.-]{1,64}$")


def _env_ctx() -> Optional[Tuple[str, str]]:
    raw = os.environ.get(TRACE_CTX_ENV, "")
    if ":" in raw:
        t, s = raw.split(":", 1)
        if _ID_RE.match(t) and _ID_RE.match(s):
            return (t, s)
    return None


_proc_ctx = _env_ctx()


def sink_active() -> bool:
    """Whether spans are being recorded — the ``obs_trace_dir`` flag."""
    return bool(core_flags.flag("obs_trace_dir"))


# span tap: the flight recorder (obs/flight.py) subscribes to the
# serialized span stream so the ring keeps recent spans even when no
# file sink is configured. One module-global pointer — None (default)
# keeps every check a single load.
_tap = None


def set_span_tap(fn) -> None:
    """Install (or clear, with None) the span-line subscriber."""
    global _tap
    _tap = fn


def _recording() -> bool:
    """Spans are generated when a sink OR a tap wants them."""
    return _tap is not None or sink_active()


# Ids are a random base + pid + counter: unique across a pod (the pid
# covers fork sharing the counter state) without paying uuid4's ~3us
# on every span (hot-path budget)
_id_base = uuid.uuid4().hex[:8]
_id_seq = itertools.count(1)


def new_trace_id() -> str:
    return f"{_id_base}{os.getpid():x}x{next(_id_seq):x}"


def new_span_id() -> str:
    return f"{_id_base}{os.getpid():x}x{next(_id_seq):x}"


def set_process_context(trace_id: Optional[str],
                        span_id: Optional[str] = None) -> None:
    """Set (or clear, with None) this process's default trace context —
    what :func:`current` falls back to when no thread-local context is
    active. Workers inherit one from ``PADDLE_OBS_TRACE_CTX``."""
    global _proc_ctx
    if trace_id is None:
        _proc_ctx = None
    else:
        _proc_ctx = (_clean_id(trace_id),
                     _clean_id(span_id) if span_id else new_span_id())


def process_context() -> Tuple[str, str]:
    """The process-default context, creating one lazily — a standalone
    training run with tracing on still gets ONE trace covering the
    whole run."""
    global _proc_ctx
    if _proc_ctx is None:
        with _lock:
            if _proc_ctx is None:
                _proc_ctx = (new_trace_id(), new_span_id())
    return _proc_ctx


def current() -> Optional[Tuple[str, str]]:
    """The active (trace_id, span_id): innermost thread-local context,
    else the process default (created lazily when the sink is active),
    else None."""
    stack = getattr(_tls, "ctx", None)
    if stack:
        return stack[-1]
    if _proc_ctx is not None:
        return _proc_ctx
    if sink_active():
        return process_context()
    return None


def _clean_id(raw) -> str:
    """Force an externally-supplied id into the token alphabet the
    hot-path serializer interpolates unescaped (a quote in a
    caller-minted id must corrupt that id, not the whole sink)."""
    s = str(raw)[:64]
    return s if _ID_RE.match(s) else (
        re.sub(r"[^A-Za-z0-9_.-]", "_", s)[:64] or "invalid")


@contextlib.contextmanager
def context(trace_id: str, span_id: str):
    """Establish (trace_id, span_id) as the current context for this
    thread (e.g. a replica adopting a request's wire context before
    submitting into its Server). Ids are sanitized to the trace token
    alphabet."""
    stack = getattr(_tls, "ctx", None)
    if stack is None:
        stack = _tls.ctx = []
    stack.append((_clean_id(trace_id), _clean_id(span_id)))
    try:
        yield
    finally:
        stack.pop()


# -- the JSONL sink ---------------------------------------------------------

# Buffered sink: spans append to an in-memory list and flush in
# batches (count/age threshold, explicit flush(), atexit) — a flush
# syscall per span showed up as ~15% of a 1ms CPU training step in the
# bench --obs gate. instant() still flushes IMMEDIATELY: its whole job
# is surviving the SIGKILL that lands a microsecond later.
_buf: List[str] = []
_last_flush = 0.0
_FLUSH_COUNT = 64
_FLUSH_S = 0.25
_atexit_wired = False


def _sink_locked():
    """Append handle to spans-<pid>.jsonl; caller holds ``_lock``.
    Fork-safe (a forked child reopens its own file) and dir-change-safe
    (test isolation, back-to-back soaks)."""
    global _file, _warned, _atexit_wired
    d = core_flags.flag("obs_trace_dir")
    if not d:
        return None
    pid = os.getpid()
    if _file is not None and _file[0] == (pid, d):
        return _file[1]
    try:
        os.makedirs(d, exist_ok=True)
        fh = open(os.path.join(d, f"spans-{pid}.jsonl"), "a")
    except OSError as e:
        if not _warned:
            _warned = True
            import warnings
            warnings.warn(f"obs_trace_dir {d!r} not writable: {e}; "
                          "tracing disabled for this process")
        return None
    if _file is not None:
        try:
            _flush_locked(_file[1])
            _file[1].close()
        except OSError:  # pragma: no cover
            pass
    _file = ((pid, d), fh)
    if not _atexit_wired:
        _atexit_wired = True
        atexit.register(flush)
    return fh


def _flush_locked(fh=None) -> None:
    global _last_flush
    if fh is None:
        fh = _file[1] if _file is not None else None
    if fh is None or not _buf:
        _buf.clear()
        return
    try:
        fh.write("".join(_buf))
        fh.flush()
    except (OSError, ValueError):
        pass  # tracing must never kill the work it observes
    _buf.clear()
    _last_flush = time.monotonic()


def flush() -> None:
    """Drain the span buffer to disk (batch boundary, exit, or before
    a same-process read). Writes to the last-opened sink file — a
    record can only have been buffered while that sink was active, so
    this stays correct even after the flag was cleared."""
    with _lock:
        _flush_locked()


def _write_line(line: str, flush_now: bool = False) -> None:
    tap = _tap
    if tap is not None:
        try:
            tap(line)
        except Exception:  # noqa: broad-except — the flight ring must
            # never kill the span stream it shadows
            pass
    with _lock:
        fh = _sink_locked()
        if fh is None:
            return
        _buf.append(line)
        if flush_now or len(_buf) >= _FLUSH_COUNT \
                or time.monotonic() - _last_flush > _FLUSH_S:
            _flush_locked(fh)


def _write(rec: dict, flush_now: bool = False) -> None:
    try:
        line = json.dumps(rec, default=repr) + "\n"
    except (TypeError, ValueError):
        return
    _write_line(line, flush_now)


# hot-path serialization: span names/cats are a small fixed set of
# code literals, so their JSON-escaped forms memoize; ids are
# _ID_RE-constrained (see adopt_header) and interpolate raw
_qcache: Dict[str, str] = {}


def _q(s: str) -> str:
    v = _qcache.get(s)
    if v is None:
        if len(_qcache) > 4096:  # dynamic names can't grow it forever
            _qcache.clear()
        v = _qcache[s] = json.dumps(str(s))
    return v


def record_span(name: str, dur_s: float,
                ctx: Optional[Tuple[str, str]] = None,
                span_id: Optional[str] = None,
                parent: Optional[str] = None,
                parents: Optional[Sequence[str]] = None,
                cat: str = "obs",
                args: Optional[dict] = None,
                end_time: Optional[float] = None) -> Optional[str]:
    """Record one completed span of ``dur_s`` seconds ending at
    ``end_time`` (epoch seconds; now when omitted). ``ctx`` supplies
    (trace_id, parent_span_id) explicitly — e.g. a resolver thread
    finishing a span another thread opened; omitted, the current
    context is used. Returns the span's id (None when the sink is
    off)."""
    if not _recording():
        return None
    if ctx is None:
        ctx = current()
    tid, parent_id = (ctx if ctx is not None else (None, None))
    if parent is not None:
        parent_id = parent
    sid = span_id or new_span_id()
    end = end_time if end_time is not None else time.time()
    rec = {"ph": "X", "name": name, "cat": cat,
           "ts": (end - dur_s) * 1e6, "dur": dur_s * 1e6,
           "pid": os.getpid(), "tid": threading.get_ident(),
           "trace": tid, "span": sid, "parent": parent_id}
    if parents:
        rec["parents"] = list(parents)
    if args:
        rec["args"] = args
    _write(rec)
    return sid


def instant(name: str, ctx: Optional[Tuple[str, str]] = None,
            cat: str = "obs", args: Optional[dict] = None) -> None:
    """Record a zero-duration marker NOW (written and flushed
    immediately — survives a SIGKILL a microsecond later, which is how
    a wedged replica's request receipt stays visible)."""
    if not _recording():
        return
    if ctx is None:
        ctx = current()
    tid, parent_id = (ctx if ctx is not None else (None, None))
    rec = {"ph": "i", "name": name, "cat": cat, "s": "p",
           "ts": time.time() * 1e6,
           "pid": os.getpid(), "tid": threading.get_ident(),
           "trace": tid, "span": new_span_id(), "parent": parent_id}
    if args:
        rec["args"] = args
    _write(rec, flush_now=True)


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _LiveSpan:
    """Hot-path span: everything inlined (no current()/record_span
    indirection, one id, one dict build) — span cost is paid per
    training step, and the bench --obs overhead gate holds the total
    per-step instrumentation under 5% of step time."""

    __slots__ = ("name", "cat", "args", "_t0", "_tid", "_parent",
                 "_sid")

    def __init__(self, name, cat, args):
        self.name, self.cat, self.args = name, cat, args

    def __enter__(self):
        stack = getattr(_tls, "ctx", None)
        if stack is None:
            stack = _tls.ctx = []
        if stack:
            self._tid, self._parent = stack[-1]
        else:
            self._tid, self._parent = _proc_ctx or process_context()
        self._sid = new_span_id()
        stack.append((self._tid, self._sid))
        self._t0 = time.time()
        return self

    def __exit__(self, *exc):
        _tls.ctx.pop()
        end = time.time()
        dur = end - self._t0
        if self.args:
            try:
                extra = ',"args":' + json.dumps(self.args, default=repr)
            except (TypeError, ValueError):
                extra = ""
        else:
            extra = ""
        parent = f'"{self._parent}"' if self._parent else "null"
        _write_line(
            f'{{"ph":"X","name":{_q(self.name)},"cat":{_q(self.cat)},'
            f'"ts":{self._t0 * 1e6:.1f},"dur":{dur * 1e6:.1f},'
            f'"pid":{os.getpid()},"tid":{threading.get_ident()},'
            f'"trace":"{self._tid}","span":"{self._sid}",'
            f'"parent":{parent}{extra}}}\n')
        return False


def span(name: str, cat: str = "obs",
         args: Optional[dict] = None):
    """Context manager timing one span under the current context (and
    making it the parent of anything opened inside). A shared no-op
    object when neither the sink nor the flight tap is armed — safe on
    hot paths."""
    if not _recording():
        return _NULL
    return _LiveSpan(name, cat, args)


# -- wire / env propagation -------------------------------------------------

def wire_header(ctx: Optional[Tuple[str, str]] = None
                ) -> Optional[Dict[str, str]]:
    """The context as a wire-frame header field ({"t": ..., "s": ...});
    None when tracing is off (the header stays byte-identical to the
    pre-obs protocol)."""
    if ctx is None:
        if not sink_active():
            return None
        ctx = current()
    if ctx is None:
        return None
    return {"t": ctx[0], "s": ctx[1]}


def adopt_header(h) -> Optional[Tuple[str, str]]:
    """Parse a wire-frame trace field back into a context tuple.
    Ids outside the token alphabet are rejected (they would need
    escaping everywhere downstream — a malformed peer gets an untraced
    request, not a corrupted sink)."""
    if not isinstance(h, dict):
        return None
    t, s = str(h.get("t") or ""), str(h.get("s") or "")
    if _ID_RE.match(t) and _ID_RE.match(s):
        return (t, s)
    return None


def env_entry() -> Optional[Tuple[str, str]]:
    """(env_key, env_value) a parent stamps into a worker's env so the
    worker joins this process's trace; None when tracing is off."""
    if not sink_active():
        return None
    tid, sid = process_context()
    return (TRACE_CTX_ENV, f"{tid}:{sid}")


# -- chrome-trace export ----------------------------------------------------

def read_spans(trace_dir: str) -> List[dict]:
    """Every span/instant record under ``trace_dir`` (all processes),
    skipping torn trailing lines. Drains this process's own buffer
    first, so a same-process export always sees its latest spans."""
    flush()
    out: List[dict] = []
    try:
        names = sorted(os.listdir(trace_dir))
    except OSError:
        return out
    for fn in names:
        if not (fn.startswith("spans-") and fn.endswith(".jsonl")):
            continue
        try:
            with open(os.path.join(trace_dir, fn)) as f:
                for ln in f:
                    ln = ln.strip()
                    if not ln:
                        continue
                    try:
                        out.append(json.loads(ln))
                    except ValueError:
                        continue  # torn final line of a killed process
        except OSError:
            continue
    return out


def _flight_records_as_spans(trace_dir: str, seen_span_ids) -> List[dict]:
    """``flight-<pid>.jsonl`` bundles (obs/flight.py) rendered onto the
    same timeline: span rows merge directly (skipping ids the live
    sinks already have — a crash dump shadows recently-flushed spans),
    step snapshots and lifecycle events become instant markers, so the
    last seconds before a crash sit next to the healthy pids' spans."""
    out: List[dict] = []
    try:
        names = sorted(os.listdir(trace_dir))
    except OSError:
        return out
    for fn in names:
        if not (fn.startswith("flight-") and fn.endswith(".jsonl")):
            continue
        try:
            with open(os.path.join(trace_dir, fn)) as f:
                lines = f.readlines()
        except OSError:
            continue
        for ln in lines:
            ln = ln.strip()
            if not ln:
                continue
            try:
                rec = json.loads(ln)
            except ValueError:
                continue  # torn row of a double-crash
            if not isinstance(rec, dict):
                continue
            if rec.get("ph"):  # a shadowed span record
                if rec.get("span") and rec["span"] in seen_span_ids:
                    continue
                out.append(rec)
                continue
            kind = rec.get("kind")
            if kind in ("step", "event", "flight_header"):
                name = {"step": "flight/step",
                        "flight_header": "flight/dump"}.get(
                            kind, f"flight/{rec.get('event', 'event')}")
                out.append({"ph": "i", "name": name, "cat": "flight",
                            "s": "p", "ts": float(rec.get("ts", 0)) * 1e6,
                            "pid": rec.get("pid", 0), "tid": 0,
                            "args": rec})
    return out


def export_chrome_trace(trace_dir: str, out_path: str,
                        trace_id: Optional[str] = None) -> dict:
    """Merge every process's span JSONL under ``trace_dir`` into ONE
    chrome://tracing JSON. Spans whose parent lives in another process
    or thread get flow events (``ph:"s"`` at the parent, ``ph:"f"`` at
    the child) so the chrome UI draws the request's path across pids;
    same-thread nesting renders as ordinary stacked slices, no arrow.
    Flight-recorder bundles (``flight-*.jsonl``) merge onto the same
    timeline as instant markers. ``trace_id`` filters to one flow.
    Returns summary stats ({"events", "flows", "pids", "traces",
    "names"}) the acceptance gate asserts on."""
    spans = read_spans(trace_dir)
    spans += _flight_records_as_spans(
        trace_dir, {s["span"] for s in spans if s.get("span")})
    if trace_id is not None:
        # keep spans OF the trace plus spans flow-linked INTO it: a
        # micro-batch dispatch span carries the first co-batched
        # request's trace id but lists every request's span as a
        # parent — it belongs to all of their filtered views
        ids = {s["span"] for s in spans
               if s.get("trace") == trace_id and s.get("span")}
        spans = [s for s in spans
                 if s.get("trace") == trace_id
                 or any(p in ids for p in (s.get("parents") or ()))
                 or s.get("parent") in ids]
    by_span: Dict[str, dict] = {}
    for s in spans:
        sid = s.get("span")
        if sid:
            by_span[sid] = s
    events: List[dict] = []
    pids = set()
    traces = set()
    flows = 0
    flow_id = 0
    for s in spans:
        pids.add(s.get("pid"))
        if s.get("trace"):
            traces.add(s["trace"])
        ev = {"name": s.get("name", "?"), "cat": s.get("cat", "obs"),
              "ph": s.get("ph", "X"), "ts": s.get("ts", 0),
              "pid": s.get("pid", 0), "tid": s.get("tid", 0),
              "args": dict(s.get("args") or {})}
        if ev["ph"] == "X":
            ev["dur"] = s.get("dur", 0)
        else:
            ev["s"] = s.get("s", "p")
        for k in ("trace", "span", "parent"):
            if s.get(k):
                ev["args"][k] = s[k]
        events.append(ev)
        parent_ids = list(s.get("parents") or [])
        if s.get("parent"):
            parent_ids.append(s["parent"])
        for pid_ in parent_ids:
            p = by_span.get(pid_)
            if p is None:
                continue
            if (p.get("pid"), p.get("tid")) == (s.get("pid"),
                                                s.get("tid")):
                # same-thread nesting renders as stacked slices —
                # arrows are reserved for the cross-process/thread
                # hops the merged view exists to show
                continue
            flow_id += 1
            flows += 1
            common = {"name": "flow", "cat": "obs", "id": flow_id}
            events.append({**common, "ph": "s",
                           "ts": p.get("ts", 0) + 0.01,
                           "pid": p.get("pid", 0),
                           "tid": p.get("tid", 0)})
            events.append({**common, "ph": "f", "bp": "e",
                           "ts": s.get("ts", 0) + 0.01,
                           "pid": s.get("pid", 0),
                           "tid": s.get("tid", 0)})
    events.sort(key=lambda e: e.get("ts", 0))
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as f:
        json.dump({"traceEvents": events}, f)
    return {"events": len(events), "flows": flows,
            "pids": sorted(p for p in pids if p is not None),
            "traces": sorted(traces),
            "names": sorted({s.get("name", "?") for s in spans})}


def trace_pids(trace_dir: str, trace_id: str) -> List[int]:
    """The distinct pids that recorded spans for ``trace_id`` — the
    acceptance criterion's "one request across >= 3 processes"."""
    return sorted({s["pid"] for s in read_spans(trace_dir)
                   if s.get("trace") == trace_id and "pid" in s})
