"""Analytical cost attribution: FLOPs + bytes per compiled executable.

The pane of glass (ISSUE 10) can see *when* things happen but not what
they *cost*: MFU and HBM-bandwidth utilization were hand-computed in
``bench.py`` from a per-model FLOPs formula, and nothing on the hot
paths knew its own arithmetic intensity. This module derives both
numbers from the compiler itself — ``jax.jit(fn).lower(*args)
.cost_analysis()`` runs XLA's HLO cost analysis on the lowered program
(no XLA compile, one Python trace) and reports ``flops`` and
``bytes accessed`` for exactly the graph that will run. The reference
ships the same organ as its profiler's op-level FLOPs tables; here the
unit of attribution is the *executable* (one jit site x one signature),
which is the unit the TPU runtime actually dispatches.

Contract (the ``bench.py --cost`` gate):

* **exact when possible** — :func:`analyze` returns
  ``ExecutableCost(flops, bytes_accessed, source="xla_cost_analysis")``
  from the lowered HLO; the BERT acceptance run cross-checks it within
  15% of the hand-derived ``6 * params * tokens`` formula;
* **labeled fallback** — when cost analysis is unavailable (exotic
  backend, lowering failure) the tree-size heuristic kicks in
  (``source="tree_size_heuristic"``: 2 flops per parameter element per
  batch row, bytes = one read of every input leaf + one write of every
  parameter-shaped output) so consumers can tell a measured number
  from a guess;
* **cached per jit-site signature** — :func:`site_cost` memoizes by an
  engine-supplied key, so the one-time Python trace of the cost
  lowering is paid once per (site, signature), never per step;
* **zero when off** — engines only call in under ``obs_metrics`` (the
  PR 9 structural-zero discipline).

Peak-rate tables (:func:`device_peak_flops`,
:func:`device_peak_hbm_bw`) turn the per-step costs into the
``train_mfu`` / ``train_hbm_bw_util`` gauges; ``bench.py`` shares the
FLOPs table so the bench's analytic MFU and the engine's cost-model
MFU are measured against the same peak.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

__all__ = ["ExecutableCost", "analyze", "site_cost", "tree_bytes",
           "tree_size_cost", "forward_cost", "device_peak_flops",
           "device_peak_hbm_bw", "clear_cache"]


@dataclass(frozen=True)
class ExecutableCost:
    """What one dispatch of one executable costs.

    ``source`` is ``"xla_cost_analysis"`` when the numbers came from
    the lowered HLO, ``"tree_size_heuristic"`` when they are the
    labeled fallback guess — consumers (gauges, ``hapi.summary``,
    ``bench --cost``) surface the label so a heuristic can never
    masquerade as a measurement.
    """

    flops: float
    bytes_accessed: float
    source: str

    @property
    def exact(self) -> bool:
        return self.source == "xla_cost_analysis"


def tree_bytes(tree) -> int:
    """Total ``nbytes`` over a pytree's array leaves (leaves without
    ``nbytes`` — python scalars, None — count 0)."""
    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        total += int(getattr(leaf, "nbytes", 0) or 0)
    return total


def _tree_rows(tree) -> int:
    """Leading-dim row count of the first array leaf (>=1)."""
    import numpy as np
    import jax
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = np.shape(leaf)
        if shape:
            return max(int(shape[0]), 1)
    return 1


def tree_size_cost(params, batch=None, extra=None) -> ExecutableCost:
    """The labeled fallback: 2 flops per parameter element per batch
    row (one multiply-accumulate touching each weight once per row —
    a dense-forward floor, NOT a measurement), bytes = one read of
    every input tree + one parameter-sized write."""
    import numpy as np
    import jax
    p_elems = 0
    for leaf in jax.tree_util.tree_leaves(params):
        p_elems += int(np.prod(np.shape(leaf))) if np.shape(leaf) else 1
    rows = _tree_rows(batch) if batch is not None else 1
    read = tree_bytes(params) + tree_bytes(batch) + tree_bytes(extra)
    return ExecutableCost(flops=2.0 * p_elems * rows,
                          bytes_accessed=float(read + tree_bytes(params)),
                          source="tree_size_heuristic")


def analyze(lower_thunk: Callable[[], Any],
            fallback: Optional[ExecutableCost] = None) -> ExecutableCost:
    """Run ``lower_thunk()`` (returning a ``jax.stages.Lowered``) and
    read XLA's cost analysis off it. Any failure — lowering error,
    backend without cost analysis, missing keys — degrades to
    ``fallback`` (or a zero-cost heuristic record), never an exception:
    cost attribution must not be able to kill the step it measures."""
    try:
        lowered = lower_thunk()
        cost = lowered.cost_analysis()
        # jax returns a dict (or a 1-list of dicts from Compiled)
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        if cost and "flops" in cost:
            return ExecutableCost(
                flops=float(cost.get("flops", 0.0) or 0.0),
                bytes_accessed=float(cost.get("bytes accessed", 0.0)
                                     or 0.0),
                source="xla_cost_analysis")
    except Exception:  # noqa: broad-except — cost attribution is
        # telemetry; a lowering quirk must degrade to the labeled
        # heuristic, never kill the training/serving step it measures
        pass
    if fallback is not None:
        return fallback
    return ExecutableCost(0.0, 0.0, source="tree_size_heuristic")


# -- per-site cache ---------------------------------------------------------

_cache_lock = threading.Lock()
_site_cache: Dict[Tuple, ExecutableCost] = {}


def site_cost(site: str, signature: Tuple,
              lower_thunk: Callable[[], Any],
              fallback: Optional[ExecutableCost] = None
              ) -> ExecutableCost:
    """Memoized :func:`analyze`: one Python trace per (site,
    signature), shared process-wide — the same executable dispatched
    by two engines costs one analysis."""
    key = (site, signature)
    c = _site_cache.get(key)
    if c is None:
        c = analyze(lower_thunk, fallback=fallback)
        with _cache_lock:
            c = _site_cache.setdefault(key, c)
    return c


def clear_cache() -> None:
    """Drop every cached site cost (test isolation)."""
    with _cache_lock:
        _site_cache.clear()


# -- model-level forward cost (hapi.summary / paddle.flops) -----------------

def forward_cost(net, input_size, dtype="float32") -> ExecutableCost:
    """FLOPs + bytes of one compiled eval forward of ``net`` at
    ``input_size`` (batch included) — the ``paddle.summary`` /
    ``paddle.flops`` parity surface. Falls back to the labeled
    tree-size heuristic when cost analysis is unavailable."""
    import jax
    import jax.numpy as jnp
    from ..incubate.functional import functional_call
    params = net.functional_state()
    x = jnp.zeros(tuple(input_size), jnp.dtype(dtype))
    fb = tree_size_cost(params, batch=x)
    return analyze(
        lambda: jax.jit(
            lambda p, a: functional_call(net, p, a)).lower(params, x),
        fallback=fb)


# -- peak-rate tables -------------------------------------------------------

def _resolve_device_kind(device) -> str:
    """Normalized device-kind string. The axon tunnel device
    advertises the generation via PALLAS_AXON_TPU_GEN when device_kind
    is opaque — ONE resolution shared by both peak tables, so a
    detection fix can never update one denominator and not the
    other."""
    kind = getattr(device, "device_kind", "").lower()
    if not kind.strip() or "axon" in kind:
        kind = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    return kind


# (peak_bf16_flops, peak_hbm_bytes_per_s) per generation, published
# specs; first matching needle wins, conservative default last (CPU
# runs report nominal — not physical — MFU/bandwidth utilization)
_PEAKS = (
    (("v5 lite", "v5e", "v5lite"), (197e12, 819e9)),
    (("v5p", "v5"), (459e12, 2765e9)),
    (("v4",), (275e12, 1228e9)),
    (("v6", "trillium"), (918e12, 1640e9)),
)
_PEAK_DEFAULT = (197e12, 819e9)


def _peaks(device):
    kind = _resolve_device_kind(device)
    for needles, peaks in _PEAKS:
        if any(n in kind for n in needles):
            return peaks
    return _PEAK_DEFAULT


def device_peak_flops(device) -> float:
    """bf16 peak FLOP/s per chip by device kind (the bench.py table,
    promoted here so the bench's analytic MFU and the engine's
    cost-model MFU divide by the same peak)."""
    return _peaks(device)[0]


def device_peak_hbm_bw(device) -> float:
    """Peak HBM bandwidth (bytes/s) per chip by device kind — the
    denominator of ``train_hbm_bw_util``."""
    return _peaks(device)[1]
