"""Structured JSONL lifecycle journal (ISSUE 10 tentpole, part 3).

The stack's lifecycle moments — worker restart, world resize, model
deploy, overload shed, sample quarantine, checkpoint commit — were
print/warn scatter: greppable at best, unparseable at scale. With the
``obs_events_file`` flag (or the ``PADDLE_OBS_EVENTS`` env var a parent
stamps into worker env) set, :func:`emit` appends one JSON object per
event::

    {"ts": 1754300000.123, "pid": 4242, "event": "worker_restart",
     "rank": 3, "incarnation": 2}

Appends are single ``write()`` calls on an ``O_APPEND`` handle, so many
processes share one journal without interleaving torn lines. Disabled
(the default) an emit is one flag read and an early return; enabled it
must never kill the work it observes — write failures warn once and
stop trying. The human-readable prints/warns stay — the journal is for
machines, the console for people.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

__all__ = ["EVENTS_ENV", "emit", "events_path", "read_events",
           "set_flight_tap"]

EVENTS_ENV = "PADDLE_OBS_EVENTS"

_lock = threading.Lock()
_file = None   # (pid, path, fh) — reopened after fork or path change
_warned = False

# lifecycle tap: the flight recorder (obs/flight.py) subscribes so its
# ring keeps recent events even without a journal file configured.
# None (default) keeps the disabled emit a flag read + pointer test.
_flight_tap = None


def set_flight_tap(fn) -> None:
    """Install (or clear, with None) the lifecycle-record subscriber."""
    global _flight_tap
    _flight_tap = fn


def events_path() -> str:
    """The active journal path: the ``obs_events_file`` flag, else the
    ``PADDLE_OBS_EVENTS`` env var, else '' (disabled)."""
    from ..core import flags as core_flags
    return (core_flags.flag("obs_events_file")
            or os.environ.get(EVENTS_ENV, ""))


def emit(event: str, **fields) -> None:
    """Append one lifecycle record; no-op when no journal is
    configured. ``fields`` must be JSON-serializable or reprable."""
    global _file, _warned
    path = events_path()
    tap = _flight_tap
    if not path and tap is None:
        return
    rec = {"ts": round(time.time(), 6), "pid": os.getpid(),
           "event": str(event)}
    rec.update(fields)
    if tap is not None:
        try:
            tap(rec)
        except Exception:  # noqa: broad-except — the flight ring must
            # never kill the lifecycle moment it records
            pass
    if not path:
        return
    try:
        line = json.dumps(rec, default=repr) + "\n"
    except (TypeError, ValueError):
        line = json.dumps({"ts": rec["ts"], "pid": rec["pid"],
                           "event": rec["event"],
                           "fields": repr(fields)}) + "\n"
    with _lock:
        pid = os.getpid()
        if _file is None or _file[0] != pid or _file[1] != path:
            try:
                d = os.path.dirname(path)
                if d:
                    os.makedirs(d, exist_ok=True)
                _file = (pid, path, open(path, "a"))
            except OSError as e:
                if not _warned:
                    _warned = True
                    import warnings
                    warnings.warn(
                        f"obs events file {path!r} not writable: {e}; "
                        "lifecycle journal disabled for this process")
                _file = (pid, path, None)
        fh = _file[2]
        if fh is None:
            return
        try:
            fh.write(line)
            fh.flush()
        except (OSError, ValueError):
            pass  # the journal must never kill the work it observes


def read_events(path: Optional[str] = None) -> list:
    """Parse the journal back (tests/tools), skipping torn lines."""
    path = path or events_path()
    out = []
    if not path:
        return out
    try:
        with open(path) as f:
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    out.append(json.loads(ln))
                except ValueError:
                    continue
    except OSError:
        pass
    return out
