"""Legacy reader decorators (reference python/paddle/reader/decorator.py):
pre-2.0 input pipelines compose generator factories —
``paddle.batch(paddle.reader.shuffle(train(), buf_size=500), 64)``.
Modern code uses paddle1_tpu.io.DataLoader; this module keeps the old
scripts runnable."""

from __future__ import annotations

import itertools
import random
from typing import Callable, Iterable

__all__ = ["shuffle", "buffered", "compose", "chain", "map_readers",
           "firstn", "cache", "multiprocess_reader", "xmap_readers",
           "ComposeNotAligned"]


def shuffle(reader: Callable, buf_size: int):
    """Buffered shuffle (decorator.py shuffle): fill a buf_size window,
    yield in random order."""
    def impl():
        buf = []
        for s in reader():
            buf.append(s)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                yield from buf
                buf = []
        random.shuffle(buf)
        yield from buf
    return impl


def buffered(reader: Callable, size: int):
    """Background-buffered reader. The modern DataLoader owns real
    prefetch; here a simple bounded deque keeps the API contract."""
    def impl():
        from collections import deque
        buf: deque = deque()
        it = reader()
        for s in it:
            buf.append(s)
            if len(buf) >= size:
                yield buf.popleft()
        while buf:
            yield buf.popleft()
    return impl


def map_readers(func: Callable, *readers: Callable):
    def impl():
        for samples in zip(*[r() for r in readers]):
            yield func(*samples)
    return impl


class ComposeNotAligned(ValueError):
    """Raised when composed readers yield different sample counts and
    check_alignment=True (reference decorator.py ComposeNotAligned)."""


def compose(*readers: Callable, check_alignment: bool = True):
    def impl():
        iters = [r() for r in readers]
        if check_alignment:
            sentinel = object()
            zipper = (outs for outs in
                      itertools.zip_longest(*iters, fillvalue=sentinel)
                      if _aligned(outs, sentinel))
        else:
            # stop at the shortest reader — the reference never
            # fabricates padding samples
            zipper = zip(*iters)
        for outs in zipper:
            flat = []
            for o in outs:
                if isinstance(o, tuple):
                    flat.extend(o)
                else:
                    flat.append(o)
            yield tuple(flat)
    return impl


def _aligned(outs, sentinel):
    if any(o is sentinel for o in outs):
        raise ComposeNotAligned(
            "compose: readers yielded different numbers of samples "
            "(pass check_alignment=False to truncate at the shortest)")
    return True


def chain(*readers: Callable):
    def impl():
        for r in readers:
            yield from r()
    return impl


def firstn(reader: Callable, n: int):
    def impl():
        yield from itertools.islice(reader(), n)
    return impl


def cache(reader: Callable):
    all_data = None

    def impl():
        nonlocal all_data
        if all_data is None:
            all_data = list(reader())
        yield from all_data
    return impl


def xmap_readers(mapper: Callable, reader: Callable, process_num: int = 1,
                 buffer_size: int = 0, order: bool = False):
    """Parallel map (decorator.py xmap_readers). Thread pool keeps
    ordering when asked; heavy parallel IO belongs in DataLoader."""
    def impl():
        from collections import deque
        from concurrent.futures import ThreadPoolExecutor
        window = max(process_num * 2, buffer_size or 0, 2)
        with ThreadPoolExecutor(max_workers=max(1, process_num)) as ex:
            pending = deque()
            for s in reader():          # lazy submission: bounded window
                pending.append(ex.submit(mapper, s))
                if len(pending) >= window:
                    yield pending.popleft().result()
            while pending:
                yield pending.popleft().result()
    return impl


def multiprocess_reader(readers, use_pipe: bool = True,
                        queue_size: int = 1000):
    """Compat: serial chain (the multiprocess analog is
    paddle1_tpu.io.DataLoader(num_workers=N))."""
    return chain(*readers)
