"""Build/system configuration introspection (reference
python/paddle/sysconfig.py: get_include/get_lib)."""

import os

__all__ = ["get_include", "get_lib"]

_PKG = os.path.dirname(os.path.abspath(__file__))


def get_include() -> str:
    """Directory of C headers for extension building (the native runtime's
    source tree; the reference returns its bundled fluid headers)."""
    return os.path.join(_PKG, "core", "native", "src")


def get_lib() -> str:
    """Directory containing the native shared library."""
    return os.path.join(_PKG, "core", "native")
