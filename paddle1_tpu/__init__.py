"""paddle1_tpu: a TPU-native deep-learning framework with the capability
surface of PaddlePaddle (reference ≈ v2.0), built on JAX/XLA/Pallas.

Eager mode = tape autograd over jax ops (the dygraph analog); compiled mode =
whole-graph jit/pjit (the static-graph analog); distribution = named mesh
axes + XLA collectives (the fleet analog). See SURVEY.md at the repo root for
the full mapping to the reference.
"""

__version__ = "0.1.0"

from .core import (CPUPlace, Place, TPUPlace, Tensor, bfloat16, bool_,
                   complex128, complex64, device_count, device_guard,
                   errors, flags, float16, float32, float64,
                   get_default_dtype, get_device, get_flags, int16, int32,
                   int64, int8, is_compiled_with_tpu, promote_types, seed,
                   set_default_dtype, set_device, set_flags, to_tensor,
                   uint8)
from .core.dtype import dtype
from .core.generator import get_rng_state, set_rng_state
from .core.tensor import Parameter
from .autograd import grad, no_grad, enable_grad, set_grad_enabled, \
    is_grad_enabled
from .ops import *  # noqa: F401,F403 — tensor op namespace (also patches Tensor)
from .ops import linalg
from . import autograd

# Subsystems are imported lazily-but-eagerly as they land; keep this list in
# sync with the build plan (SURVEY.md §7).
from . import nn
from . import optimizer
from . import profiler
from . import distribution
from . import sysconfig
from . import onnx
from . import quantization
from . import amp
from . import io
from . import metric
from . import jit
from . import static
from . import distributed
from . import inference
from . import utils
from . import hub
from . import vision
from . import text
from . import hapi
from . import incubate
from . import metric as metrics  # compat alias
from .framework import save, load
from .jit import to_static
from .hapi.model import Model
from .hapi.model_summary import summary, flops

# paddle-compat aliases
def disable_static(place=None):
    return None  # eager is the default mode


def enable_static():
    from .static import enable_static_mode
    enable_static_mode()
