"""paddle1_tpu: a TPU-native deep-learning framework with the capability
surface of PaddlePaddle (reference ≈ v2.0), built on JAX/XLA/Pallas.

Eager mode = tape autograd over jax ops (the dygraph analog); compiled mode =
whole-graph jit/pjit (the static-graph analog); distribution = named mesh
axes + XLA collectives (the fleet analog). See SURVEY.md at the repo root for
the full mapping to the reference.
"""

__version__ = "0.1.0"

from .core import (CPUPlace, Place, TPUPlace, Tensor, bfloat16, bool_,
                   complex128, complex64, device_count, device_guard,
                   errors, flags, float16, float32, float64,
                   get_default_dtype, get_device, get_flags, int16, int32,
                   int64, int8, is_compiled_with_tpu, promote_types, seed,
                   set_default_dtype, set_device, set_flags, to_tensor,
                   uint8)
from .core.dtype import dtype
from .core.generator import get_rng_state, set_rng_state
from .core.tensor import Parameter
from .autograd import grad, no_grad, enable_grad, set_grad_enabled, \
    is_grad_enabled
from .ops import *  # noqa: F401,F403 — tensor op namespace (also patches Tensor)
from .ops import linalg
from . import autograd

# Subsystems are imported lazily-but-eagerly as they land; keep this list in
# sync with the build plan (SURVEY.md §7).
from . import nn
from . import optimizer
from . import profiler
from . import distribution
from . import sysconfig
from . import onnx
from . import quantization
from . import amp
from . import io
from . import metric
from . import jit
from . import static
from . import distributed
from . import inference
from . import serving
from . import utils
from . import hub
from . import vision
from . import text
from . import hapi
from . import incubate
from . import metric as metrics  # compat alias
from .framework import save, load
from .jit import to_static
from .hapi.model import Model
from .hapi.model_summary import summary, flops

# paddle-compat aliases
def disable_static(place=None):
    return None  # eager is the default mode


def enable_static():
    from .static import enable_static_mode
    enable_static_mode()


# -- pre-2.0 top-level compat (reference python/paddle/__init__.py names
# that old scripts touch; the heavyweight surface lives in paddle1_tpu.fluid)
from . import reader  # noqa: E402  (legacy reader decorators)
from . import regularizer  # noqa: E402
from .distributed import DataParallel  # noqa: E402
from .framework.param_attr import ParamAttr  # noqa: E402
from .hapi import callbacks  # noqa: E402

VarBase = Tensor  # dygraph-era tensor name
CUDAPlace = TPUPlace  # old scripts mean "the accelerator"


class CUDAPinnedPlace:  # host-pinned staging has no TPU analog
    def __repr__(self):
        return "CUDAPinnedPlace (compat: host memory)"


def batch(reader_fn, batch_size, drop_last=False):
    """The classic reader batcher (reference python/paddle/reader —
    ``paddle.batch(train(), 64)``); yields lists of samples."""
    def impl():
        buf = []
        for s in reader_fn():
            buf.append(s)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return impl


def in_dygraph_mode() -> bool:
    return True


in_dynamic_mode = in_dygraph_mode


def enable_dygraph(place=None):
    return None


def disable_dygraph():
    from .fluid import disable_dygraph as _impl
    _impl()


def is_compiled_with_cuda() -> bool:
    return False  # TPU build


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False


# is_compiled_with_tpu comes from core (line ~16): a REAL device probe,
# not a constant — scripts branch on it to pick CPUPlace vs TPUPlace


def get_cudnn_version():
    return None  # no cuDNN in the TPU stack


def get_cuda_rng_state():
    return get_rng_state()  # the accelerator RNG state


def set_cuda_rng_state(state):
    return set_rng_state(state)


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    from .nn.layer_base import Layer
    return Layer().create_parameter(shape, attr=attr, dtype=dtype,
                                    is_bias=is_bias,
                                    default_initializer=default_initializer)


def rank(input):
    """Tensor rank as a 0-d int tensor (reference layers rank op)."""
    import numpy as _np
    return to_tensor(_np.asarray(Tensor(input).ndim
                                 if not isinstance(input, Tensor)
                                 else input.ndim, _np.int32))


def is_empty(x, name=None):
    import numpy as _np
    t = x if isinstance(x, Tensor) else to_tensor(x)
    return to_tensor(_np.asarray(t.size == 0))


def reverse(x, axis, name=None):
    from .ops import manip_ops as _m
    ax = axis if isinstance(axis, (list, tuple)) else [axis]
    return _m.flip(x, ax)


def tolist(x):
    return (x if isinstance(x, Tensor) else to_tensor(x)).numpy().tolist()


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Numpy-backed print options (Tensor repr renders via numpy)."""
    import numpy as _np
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


full_version = __version__


bool = bool_  # dtype spelling (paddle.bool)


class NPUPlace:
    def __init__(self, *a):
        raise RuntimeError("NPU is not a target of this TPU build; "
                           "devices are CPUPlace / TPUPlace")


class XPUPlace:
    def __init__(self, *a):
        raise RuntimeError("XPU is not a target of this TPU build; "
                           "devices are CPUPlace / TPUPlace")


def crop_tensor(x, shape=None, offsets=None, name=None):
    """Old spelling of the crop op (reference crop_tensor; one cropper —
    ops.manip_ops.crop — owns the arithmetic)."""
    t = x if isinstance(x, Tensor) else to_tensor(x)
    if shape is None:
        shape = [-1] * t.ndim
    shape = [-1 if s is None else s for s in shape]
    from .ops import manip_ops as _m
    return _m.crop(t, shape=shape, offsets=offsets)


from . import version  # noqa: E402  (paddle.version.show() etc.)
commit = version.commit
