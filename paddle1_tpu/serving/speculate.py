"""Draft proposal for speculative decoding (ISSUE 16).

The engine verifies ``k`` proposed tokens per decode dispatch by
equality against its own deterministic per-request sample chain (see
``generate.GenerationEngine``), so a speculator is pure upside: a wrong
draft costs nothing but the wasted window width, a right one turns k+1
tokens into one dispatch. Correctness never depends on the speculator —
output is bit-identical to non-speculative decode whatever it proposes.

:class:`NGramSpeculator` is the zero-model prompt-lookup speculator
(the "n-gram" mode of the reference's FastGeneration
``decode_strategy`` family, and the common production baseline): the
draft is the continuation of the most recent earlier occurrence of the
last ``n`` tokens in the request's own history (prompt + generated),
falling back to shorter grams. It wins exactly where speculation pays —
repetitive/templated text — and proposes nothing on fresh text.

:class:`DraftModelSpeculator` adapts any greedy-decoding callable
(e.g. a smaller CausalLM) to the same ``propose`` protocol.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import numpy as np

__all__ = ["NGramSpeculator", "DraftModelSpeculator"]


class NGramSpeculator:
    """Prompt-lookup drafts over one request's token history.

    ``observe`` feeds every produced token (the engine feeds the prompt
    at construction and each delivered token after); ``propose``
    returns up to ``k`` draft tokens following the current history —
    the continuation found after the latest earlier occurrence of the
    trailing n-gram, trying ``n`` down to 1. Empty proposal = decode
    proceeds non-speculatively for that step (zero-width drafts cost
    nothing device-side).
    """

    def __init__(self, prompt: Sequence[int], k: int, n: int = 3):
        self.k = int(k)
        self.n = max(1, int(n))
        self._hist: List[int] = [int(t) for t in np.asarray(
            prompt).reshape(-1)]

    def observe(self, token: int) -> None:
        self._hist.append(int(token))

    @property
    def history(self) -> List[int]:
        return list(self._hist)

    def propose(self, k: Optional[int] = None) -> np.ndarray:
        k = self.k if k is None else min(int(k), self.k)
        h = self._hist
        if k <= 0 or len(h) < 2:
            return np.zeros([0], np.int32)
        for n in range(min(self.n, len(h) - 1), 0, -1):
            gram = h[-n:]
            # latest earlier occurrence scan (right-to-left, excluding
            # the trailing occurrence itself) — but prefer the most
            # recent occurrence whose continuation fills the whole
            # window: on short-cycle text every near-tail match has its
            # continuation truncated by the tail, while one a period
            # earlier drafts k tokens (the case speculation exists for)
            best: List[int] = []
            for s in range(len(h) - n - 1, -1, -1):
                if h[s:s + n] == gram:
                    cont = h[s + n:s + n + k]
                    if len(cont) > len(best):
                        best = cont
                        if len(best) == k:
                            return np.asarray(best, np.int32)
            if best:
                return np.asarray(best, np.int32)
        return np.zeros([0], np.int32)


class DraftModelSpeculator:
    """A small model as the draft source: ``draft_fn(history, k)`` must
    return up to ``k`` draft ints (greedy continuation of ``history``).
    Same observe/propose protocol as :class:`NGramSpeculator`, so the
    engine treats both identically."""

    def __init__(self, prompt: Sequence[int], k: int,
                 draft_fn: Callable[[List[int], int], Sequence[int]]):
        self.k = int(k)
        self._draft_fn = draft_fn
        self._hist: List[int] = [int(t) for t in np.asarray(
            prompt).reshape(-1)]

    def observe(self, token: int) -> None:
        self._hist.append(int(token))

    @property
    def history(self) -> List[int]:
        return list(self._hist)

    def propose(self, k: Optional[int] = None) -> np.ndarray:
        k = self.k if k is None else min(int(k), self.k)
        if k <= 0:
            return np.zeros([0], np.int32)
        out = np.asarray(list(self._draft_fn(list(self._hist), k)),
                         np.int32).reshape(-1)[:k]
        return out.astype(np.int32)
