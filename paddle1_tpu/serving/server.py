"""Serving front end: admission control, deadlines, graceful drain.

The :class:`Server` is the piece a serving *worker process* wraps around
an :class:`~paddle1_tpu.serving.engine.InferenceEngine`: clients
``submit()`` requests and get futures back; a bounded queue sheds
overload with the typed :class:`ServerOverloaded` (fail fast at the
door — an unbounded queue converts overload into every request blowing
its deadline); per-request deadlines fail late requests with
:class:`DeadlineExceeded`; and SIGTERM (or
``core.health.request_drain()``) triggers the graceful-drain protocol —
stop admitting, flush everything already accepted, report — wired
through the same ``core/health`` channel PR 3's Supervisor speaks, so a
serving worker is supervised (heartbeats, hang detection, restart,
drain) exactly like a training worker.

Accounting invariant (the no-silent-drops contract, asserted by the
drain tests): every accepted request resolves — success, typed deadline
failure, or typed error. ``drain()`` returns a report proving it.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Optional, Sequence

import numpy as np

from ..core import flags as core_flags
from ..core import health as core_health
from ..core import locks
from ..core.errors import InvalidArgumentError, PreconditionNotMetError
from .batcher import Batcher, ServeFuture, _Request
from .engine import InferenceEngine
from .errors import ServerClosed, ServerOverloaded
from .metrics import ServingMetrics

__all__ = ["Server", "install_standalone_sigterm_drain"]


def install_standalone_sigterm_drain() -> None:
    """For an UNSUPERVISED serving worker on the main thread: make
    SIGTERM mean "drain", not "die with the queue full", by chaining a
    ``core.health.request_drain()`` in front of whatever handler the
    script installed. Idempotent per process — a restart-after-drain
    loop must not wrap our own handler in a fresh closure each cycle
    (an N-deep chain re-running request_drain N times per SIGTERM).
    Shared by :class:`Server` and the generation server."""
    import signal
    prev = signal.getsignal(signal.SIGTERM)
    if getattr(prev, "_p1_serving_drain", False):
        return

    def _on_sigterm(signum, frame, _prev=prev):
        core_health.request_drain()  # fans out to subscribers
        if callable(_prev):
            _prev(signum, frame)
    _on_sigterm._p1_serving_drain = True
    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except (OSError, ValueError):  # pragma: no cover
        pass  # exotic host; drain() still works programmatically


class Server:
    """Micro-batching inference server over one model.

    Parameters (``None`` → the ``serve_*`` flag defaults)
    ----------------------------------------------------
    model : anything :class:`InferenceEngine` accepts (Layer,
        Predictor/TranslatedLayer, plain callable) or a pre-built
        engine.
    max_batch : micro-batch row ceiling (≤ the engine's largest bucket).
    batch_timeout_ms : how long an incomplete batch waits for company.
    queue_depth : admitted-but-undispatched request bound (admission
        control; beyond it ``submit`` sheds with ``ServerOverloaded``).
    deadline_ms : default per-request deadline (0/None → none).
    warmup : pre-compile every bucket in ``start()`` (needs
        ``input_specs`` — automatic for Predictor artifacts).
    delta_dir : watch this embedding-delta log directory (ISSUE 19
        online learning) and apply each published version to the
        engine's params live — no recompile, no redeploy.
    delta_poll_ms : delta log poll interval (default 50ms; bounds the
        publish-to-servable latency together with one dispatch).
    """

    def __init__(self, model, max_batch: Optional[int] = None,
                 batch_timeout_ms: Optional[float] = None,
                 queue_depth: Optional[int] = None,
                 buckets=None, input_specs=None,
                 deadline_ms: Optional[float] = None,
                 warmup: bool = False,
                 metrics: Optional[ServingMetrics] = None,
                 delta_dir: Optional[str] = None,
                 delta_poll_ms: Optional[float] = None):
        self.metrics = metrics if metrics is not None else ServingMetrics()
        if isinstance(model, InferenceEngine):
            if buckets is not None or input_specs is not None:
                raise InvalidArgumentError(
                    "buckets/input_specs cannot be applied to a "
                    "pre-built InferenceEngine (its executables are "
                    "already keyed) — pass them to InferenceEngine(), "
                    "or hand Server the raw model")
            self.engine = model
            # latest-wins: the server currently serving the engine owns
            # the compile/warmup mirroring (a reused engine would
            # otherwise report into the first, long-discarded registry)
            self.engine.metrics = self.metrics
        else:
            self.engine = InferenceEngine(
                model, buckets=buckets, max_batch=max_batch,
                input_specs=input_specs, metrics=self.metrics)
        if max_batch is None:
            # default clamps to the engine's top bucket, so explicit
            # buckets (1,4) aren't tripped up by the flag's 16 default
            self.max_batch = min(
                int(core_flags.flag("serve_max_batch")),
                self.engine.max_batch)
        else:
            self.max_batch = int(max_batch)
        if self.max_batch > self.engine.max_batch:
            raise InvalidArgumentError(
                f"max_batch={self.max_batch} exceeds the engine's "
                f"largest bucket {self.engine.max_batch} — a full "
                "micro-batch would be undispatchable")
        self.batch_timeout_ms = float(
            batch_timeout_ms if batch_timeout_ms is not None
            else core_flags.flag("serve_batch_timeout_ms"))
        self.queue_depth = int(queue_depth if queue_depth is not None
                               else core_flags.flag("serve_queue_depth"))
        dl = deadline_ms if deadline_ms is not None \
            else core_flags.flag("serve_deadline_ms")
        self.default_deadline_ms = float(dl) if dl else None
        self._warmup = bool(warmup)
        self._q: "queue.Queue[_Request]" = queue.Queue(self.queue_depth)
        self._drain_event = threading.Event()
        # makes {accepting-check → requests_total → enqueue} atomic
        # against drain()'s accepting-flip: without it a drain landing
        # between the count and the put snapshots accepted=completed+1
        # and reports unaccounted=1 for a request that resolves typed a
        # beat later (uncontended acquire is ~100ns — no convoy)
        self._admit_lock = locks.make_lock("Server._admit_lock")
        self._accepting = False          # guarded-by: self._admit_lock
        self._batcher: Optional[Batcher] = None
        self.delta_dir = delta_dir
        self.delta_poll_s = float(
            delta_poll_ms if delta_poll_ms is not None else 50.0) / 1e3
        self._delta_sub = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "Server":
        """Start the batcher thread (idempotent). Call from the main
        thread: adopting the Supervisor's health channel installs the
        SIGTERM→drain handler, which Python only allows there."""
        if self._batcher is not None and self._batcher.is_alive():
            return self
        # restart-after-drain reopens the server: clear the stale drain
        # latch BEFORE resubscribing, or the fresh batcher below would
        # exit on its first pass and every submit would see ServerClosed.
        # A process-level drain (SIGTERM) pending right now is re-latched
        # by the drain_requested() check just after.
        self._drain_event.clear()
        # adopt the supervisor heartbeat channel (no-op unsupervised)
        # and subscribe this server to drain requests — a SIGTERM while
        # loaded stops admission and flushes, it never drops work
        supervised = core_health.supervised()
        core_health.beat()
        core_health.add_drain_callback(self._drain_event.set)
        if core_health.drain_requested():
            self._drain_event.set()
        if not supervised and threading.current_thread() is \
                threading.main_thread():
            # standalone worker (no Supervisor → health installed no
            # handler): SIGTERM must still mean "drain", not "die with
            # the queue full". Chain whatever the script installed.
            install_standalone_sigterm_drain()
        if self._warmup:
            n = self.engine.warm_up()
            self.metrics.counter("warmup_buckets_total").inc(n)
        self._batcher = Batcher(self.engine, self._q, self.max_batch,
                                self.batch_timeout_ms, self.metrics,
                                self._drain_event)
        self._batcher.start()
        if self.delta_dir and self._delta_sub is None:
            # fail fast on a bad delta_dir: a typo here would otherwise
            # serve stale embeddings forever while the poll loop spins
            # on a directory nobody publishes into
            if not os.path.isdir(self.delta_dir):
                raise InvalidArgumentError(
                    f"Server(delta_dir={self.delta_dir!r}) names a "
                    "directory that does not exist. Point it at the "
                    "trainer's DeltaLog directory (DeltaLog creates it "
                    "at construction), or create it before start() — "
                    "a replica polling a nonexistent path would serve "
                    "stale embeddings forever without an error")
            if not os.access(self.delta_dir, os.R_OK | os.X_OK):
                raise InvalidArgumentError(
                    f"Server(delta_dir={self.delta_dir!r}) is not "
                    "readable by this process — fix the directory "
                    "permissions; the delta subscriber needs to list "
                    "and read the trainer-published delta files")
            # the online-learning consumer: trainer-published embedding
            # deltas land in the engine's live param dict between
            # dispatches (update_param_rows — shape-preserving, so it
            # never recompiles)
            from ..distributed.embedding_delta import DeltaSubscriber
            self._delta_sub = DeltaSubscriber(
                self.delta_dir, self.engine.update_param_rows,
                poll_s=self.delta_poll_s, metrics=self.metrics).start()
        with self._admit_lock:
            self._accepting = True
        return self

    @property
    def running(self) -> bool:
        return (self._batcher is not None and self._batcher.is_alive()
                and self._accepting)

    def __enter__(self) -> "Server":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.drain()
        return False

    # -- request path -------------------------------------------------------

    def submit(self, *inputs, deadline_ms: Optional[float] = None
               ) -> ServeFuture:
        """Enqueue one request (each input carries a leading batch dim;
        a plain sample may be 1-row). Returns a future; raises
        ``ServerOverloaded`` (queue full) or ``ServerClosed``
        (draining/stopped) synchronously."""
        if not self._accepting or self._drain_event.is_set():
            raise ServerClosed(
                "server is draining/stopped — not admitting requests")
        if self._batcher is None or not self._batcher.is_alive():
            raise ServerClosed(
                "server not started (or its batcher died: "
                f"{self._batcher.fatal!r})" if self._batcher is not None
                else "server not started — call start()")
        if not inputs:
            raise InvalidArgumentError("submit needs >= 1 input array")
        arrays = [np.asarray(getattr(a, "data", a)) for a in inputs]
        rows = int(np.shape(arrays[0])[0]) if np.ndim(arrays[0]) else 0
        if rows < 1:
            raise InvalidArgumentError(
                "request inputs need a leading batch dim (reshape a "
                "single sample to [1, ...])")
        if rows > self.max_batch:
            raise InvalidArgumentError(
                f"request has {rows} rows > max_batch={self.max_batch} "
                "— split it client-side")
        # every input must agree on the batch dim HERE, before enqueue:
        # a mismatched request that reached the Batcher would fail
        # pad_to_bucket at dispatch and take every innocent request
        # co-batched with it down too
        for i, a in enumerate(arrays[1:], start=1):
            if np.ndim(a) < 1 or int(np.shape(a)[0]) != rows:
                raise InvalidArgumentError(
                    f"input {i} has leading dim "
                    f"{np.shape(a)[0] if np.ndim(a) else '<scalar>'} but "
                    f"input 0 has {rows} — all inputs of one request "
                    "must share the batch dim")
        dl = deadline_ms if deadline_ms is not None \
            else self.default_deadline_ms
        req = _Request(arrays, self.engine._inner_sig(arrays),
                       dl / 1e3 if dl else None)
        from ..obs import trace as obs_trace
        if obs_trace.sink_active():
            # the submitter's context (a replica adopts the wire frame's
            # context around this call) rides the request into the
            # batcher's dispatch span
            req.trace = obs_trace.current()
        # counted BEFORE the enqueue: were it counted after, the batcher
        # could complete the request before it registered as accepted
        # and a concurrent snapshot would read unaccounted < 0. Sheds
        # increment shed_total, so accepted = requests - sheds stays
        # exact either way. The lock pairs the count with the enqueue
        # so a drain() can never snapshot between them; the accepting
        # re-check inside it closes the admission race for good.
        with self._admit_lock:
            if not self._accepting or self._drain_event.is_set():
                raise ServerClosed(
                    "server is draining/stopped — not admitting "
                    "requests")
            self.metrics.counter("requests_total").inc()
            try:
                self._q.put_nowait(req)
            except queue.Full:
                self.metrics.counter("shed_total").inc()
                raise ServerOverloaded(
                    f"queue depth {self.queue_depth} exhausted — "
                    "request shed (scale out, raise serve_queue_depth, "
                    "or slow the client)") from None
        b = self._batcher
        if self._drain_event.is_set() and b is not None \
                and b.drained.is_set():
            # lost the admission race: the lock serializes against
            # drain(), but a SIGTERM/health callback sets _drain_event
            # WITHOUT it — the batcher can flush and exit between the
            # locked re-check and here, leaving this request in a queue
            # nothing reads. Fail it typed rather than leave the future
            # unresolved (errors_total keeps it accounted).
            b._fail_queued(ServerClosed(
                "server drained while the request was being admitted"),
                wrap=False)
        return req.future

    def infer(self, *inputs, deadline_ms: Optional[float] = None,
              timeout: Optional[float] = None):
        """Synchronous convenience: ``submit(...).result()``."""
        return self.submit(*inputs,
                           deadline_ms=deadline_ms).result(timeout)

    # -- drain / shutdown ---------------------------------------------------

    def wait(self, poll_s: float = 0.1,
             timeout: Optional[float] = None) -> dict:
        """Serve until a drain is requested (SIGTERM under the
        Supervisor, ``core.health.request_drain()``, or ``timeout``),
        then drain and return the report — the serving worker's
        main-loop idiom."""
        t0 = time.monotonic()
        while not self._drain_event.is_set():
            if timeout is not None and time.monotonic() - t0 >= timeout:
                break
            core_health.beat()
            time.sleep(poll_s)
        return self.drain()

    def drain(self, timeout: float = 30.0) -> dict:
        """Graceful shutdown: stop admitting, flush every accepted
        request (complete or fail typed), join the batcher, report."""
        with self._admit_lock:
            # any submit mid-admission finishes its count+enqueue first;
            # everything counted from here on is in the queue, so the
            # sweeps below account for all of it
            self._accepting = False
            self._drain_event.set()
        if self._delta_sub is not None:
            self._delta_sub.stop()
            self._delta_sub = None
        drained = True
        if self._batcher is not None:
            drained = self._batcher.drained.wait(timeout)
            self._batcher.join(timeout=max(timeout, 1.0))
            if not drained:
                # flush stalled (a wedged executable): fail what's left
                # loudly rather than drop it silently — BOTH the
                # still-queued requests and the ones the batcher already
                # popped (mid-assembly or stuck inside the dispatch);
                # first-wins resolution means a dispatch that un-wedges
                # later can't overwrite these typed failures
                exc = PreconditionNotMetError(
                    f"drain timed out after {timeout}s")
                self._batcher._fail_queued(exc, wrap=False)
                self._batcher.fail_inflight(exc)
            # ALWAYS sweep once more after the batcher exited (no-op on
            # an empty queue): a submit() racing this drain can enqueue
            # after the batcher's final flush, and its future must
            # resolve typed, not hang
            self._batcher._fail_queued(ServerClosed(
                "server drained while the request was being admitted"),
                wrap=False)
        core_health.remove_drain_callback(self._drain_event.set)
        snap = self.metrics.snapshot()
        c = snap["counters"]
        report = {
            "drained": bool(drained),
            "fatal": (repr(self._batcher.fatal)
                      if self._batcher is not None
                      and self._batcher.fatal is not None else None),
            "accepted": (c.get("requests_total", 0)
                         - c.get("shed_total", 0)),
            "completed": c.get("responses_total", 0),
            "deadline_failed": c.get("deadline_expired_total", 0),
            "errors": c.get("errors_total", 0),
            "shed": c.get("shed_total", 0),
            "batches": c.get("batches_total", 0),
            "compile_counts": dict(self.engine.compile_counts),
        }
        report["unaccounted"] = (report["accepted"] - report["completed"]
                                 - report["deadline_failed"]
                                 - report["errors"])
        return report

    stop = drain
