"""Host-side KV page accounting for the paged decode cache (ISSUE 16).

The device half of paging is dumb on purpose — per layer, one
``[pages, page_size, heads, dim]`` pool array plus a ``[slots,
max_pages_per_slot]`` int32 page table, both riding the ONE compiled
decode signature. Everything that must not live in the trace lives
here: the free list, per-page refcounts, and the copy-on-write prefix
registry that lets N requests over one system prompt hold its prefill
pages once.

Ground rule that makes sharing exact: K/V at position ``i`` depend only
on ``(token_i, i)`` (causal attention — the projection of token ``i``
at position ``i`` never sees its successors), so a FULL page of a
prompt whose ``(token, position)`` block matches a previously-stored
one is byte-identical and can be aliased by refcount. Partial tail
pages are always private (decode writes into them); the engine never
writes a shared page — a reused page's scatter target is redirected to
the parking page — so no device-side copy-on-write fault path is
needed: the "copy" is simply "the tail page was never shared".

Page 0 is reserved as the **parking page**: free slots' (and beyond-
capacity) decode writes are directed at it so inactive slots can ride
the same dispatch without scatter-colliding into anyone's real pages.
It is never allocated and never read (every reader masks by cursor).

The registry holds one ref per page per entry; a page frees when its
refcount reaches zero (no slot and no cached prefix holds it).
Allocation under pressure LRU-evicts unshared registry entries first
and raises :class:`~paddle1_tpu.serving.errors.KVPoolExhausted` typed
only when the pool is genuinely out of pages.
"""

from __future__ import annotations

import collections
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .errors import KVPageAccountingError, KVPoolExhausted

__all__ = ["PagePool", "PARKING_PAGE"]

PARKING_PAGE = 0


class PagePool:
    """Free list + refcounts + prefix registry over ``num_pages`` KV
    pages of ``page_size`` tokens each. Purely host state — the caller
    (the GenerationEngine, single scheduler thread) owns thread safety.
    """

    def __init__(self, num_pages: int, page_size: int,
                 prefix_entries: int = 0):
        if num_pages < 2:
            raise ValueError(
                f"PagePool needs >= 2 pages (page {PARKING_PAGE} is the "
                f"reserved parking page), got {num_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.prefix_entries = int(prefix_entries)
        self._free: collections.deque = collections.deque(
            range(1, self.num_pages))
        self._refs = np.zeros(self.num_pages, np.int64)
        # key: bytes of the int32 (token) prefix covering n full pages
        # -> tuple of its n page ids; insertion order IS the LRU order
        # (move_to_end on hit).
        self._registry: "collections.OrderedDict[bytes, Tuple[int, ...]]" \
            = collections.OrderedDict()
        # cumulative event counts (the engine mirrors them as metrics)
        self.alloc_count = 0
        self.eviction_count = 0
        self.prefix_hits = 0
        self.prefix_hit_pages = 0

    # -- basic bookkeeping --------------------------------------------------

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        """Pages with any holder (slots or registry), excl. parking."""
        return (self.num_pages - 1) - len(self._free)

    @property
    def registry_pages(self) -> int:
        """Distinct pages held by cached prefixes."""
        return len({p for ids in self._registry.values() for p in ids})

    def refcount(self, page: int) -> int:
        return int(self._refs[page])

    def alloc(self, n: int) -> List[int]:
        """Claim ``n`` fresh pages (each at refcount 1), LRU-evicting
        cached prefixes under pressure; typed KVPoolExhausted when the
        pool genuinely cannot serve."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        while len(self._free) < n and self._evict_one():
            pass
        if len(self._free) < n:
            raise KVPoolExhausted(
                f"KV page pool exhausted: need {n} page(s), "
                f"{len(self._free)} free of {self.num_pages - 1} "
                f"usable ({self.registry_pages} held by cached "
                "prefixes, none evictable) — raise serve_gen_kv_pages, "
                "lower max_new_tokens/slots, or share more prefix")
        out = [self._free.popleft() for _ in range(n)]
        for p in out:
            self._refs[p] += 1
        self.alloc_count += n
        return out

    def retain(self, pages) -> None:
        for p in pages:
            if p == PARKING_PAGE:
                continue
            self._refs[p] += 1

    def release(self, pages) -> None:
        """Drop one ref per page; pages reaching zero return to the
        free list. A release of an already-free page raises typed
        BEFORE mutating anything — appending a page to the free list
        twice would hand it to two holders and silently cross-write
        their KV, which is strictly worse than failing the release."""
        for p in pages:
            if p == PARKING_PAGE:
                continue
            if self._refs[p] <= 0:
                raise KVPageAccountingError(
                    f"KV page {p} over-released (refcount already "
                    f"{int(self._refs[p])}) — slot/registry accounting "
                    "bug; free list left untouched")
            self._refs[p] -= 1
            if self._refs[p] == 0:
                self._free.append(p)

    def check_invariants(self, holders: Sequence[Sequence[int]] = ()
                         ) -> None:
        """Debug invariant sweep (``FLAGS_debug_kv_refcount``): the sum
        of refcounts must equal the refs actually held by the prefix
        registry plus every external holder chain in ``holders`` (the
        engine passes its live slots' page chains; the scheduler adds
        any chaos-held pages), the free list must be duplicate-free and
        exactly the zero-refcount pages, and the parking page must
        never be tracked. Raises typed KVPageAccountingError."""
        expected = np.zeros(self.num_pages, np.int64)
        for ids in self._registry.values():
            for p in ids:
                expected[p] += 1
        for chain in holders:
            for p in chain:
                if p == PARKING_PAGE:
                    continue
                expected[p] += 1
        free = list(self._free)
        if len(free) != len(set(free)):
            raise KVPageAccountingError(
                "KV free list holds duplicate pages: "
                f"{sorted(p for p in set(free) if free.count(p) > 1)}")
        if PARKING_PAGE in set(free) or self._refs[PARKING_PAGE] != 0:
            raise KVPageAccountingError(
                "parking page leaked into the free list / refcounts")
        free_set = set(free)
        for p in range(1, self.num_pages):
            if int(self._refs[p]) != int(expected[p]):
                raise KVPageAccountingError(
                    f"KV page {p} refcount {int(self._refs[p])} != "
                    f"{int(expected[p])} refs held by registry+holders")
            if (p in free_set) != (self._refs[p] == 0):
                raise KVPageAccountingError(
                    f"KV page {p} refcount {int(self._refs[p])} "
                    f"disagrees with free list membership "
                    f"({'free' if p in free_set else 'not free'})")
        # derived identity the drain report leans on
        if self.pages_in_use != (self.num_pages - 1) - len(free):
            raise KVPageAccountingError(
                f"pages_in_use {self.pages_in_use} != usable - free "
                f"{(self.num_pages - 1) - len(free)}")

    # -- prefix sharing -----------------------------------------------------

    @staticmethod
    def _key(tokens: np.ndarray) -> bytes:
        return np.ascontiguousarray(
            np.asarray(tokens, np.int32)).tobytes()

    def lookup_prefix(self, prompt: np.ndarray) -> List[int]:
        """Longest cached full-page chain matching ``prompt``'s head;
        returns its page ids with one ref RETAINED per page for the
        caller (the slot). Empty list = no hit."""
        if self.prefix_entries <= 0:
            return []
        prompt = np.asarray(prompt, np.int32)
        n_full = len(prompt) // self.page_size
        for n in range(n_full, 0, -1):
            key = self._key(prompt[:n * self.page_size])
            ids = self._registry.get(key)
            if ids is not None:
                self._registry.move_to_end(key)
                self.retain(ids)
                self.prefix_hits += 1
                self.prefix_hit_pages += len(ids)
                return list(ids)
        return []

    def register_prefix(self, prompt: np.ndarray, pages) -> int:
        """Cache every full-page chain of ``prompt`` (lengths 1..n so a
        later SHORTER shared prompt still hits); each entry holds one
        ref per page. Returns entries added. No-op when the registry is
        disabled."""
        if self.prefix_entries <= 0:
            return 0
        prompt = np.asarray(prompt, np.int32)
        pages = list(pages)
        n_full = min(len(prompt) // self.page_size, len(pages))
        added = 0
        for n in range(1, n_full + 1):
            key = self._key(prompt[:n * self.page_size])
            if key in self._registry:
                self._registry.move_to_end(key)
                continue
            ids = tuple(pages[:n])
            self.retain(ids)
            self._registry[key] = ids
            added += 1
        while len(self._registry) > self.prefix_entries:
            if not self._evict_one():
                break
        return added

    def _evict_one(self) -> bool:
        """Drop the least-recently-used registry entry. Eviction only
        removes the registry's own refs, so pages still held by live
        slots (or by longer cached chains) survive; truly idle ones
        return to the free list. Returns False when the registry is
        empty (nothing left to evict)."""
        if not self._registry:
            return False
        _key, ids = self._registry.popitem(last=False)
        self.release(ids)
        self.eviction_count += 1
        return True

    def stats(self) -> Dict[str, int]:
        return {
            "pages_total": self.num_pages - 1,  # usable (excl. parking)
            "pages_free": self.free_pages,
            "pages_in_use": self.pages_in_use,
            "pages_cached": self.registry_pages,
            "prefix_entries": len(self._registry),
            "evictions": self.eviction_count,
            "prefix_hits": self.prefix_hits,
            "prefix_hit_pages": self.prefix_hit_pages,
        }
