"""Generation replica worker: the subprocess half of the GenerationFleet.

``python -m paddle1_tpu.serving.genreplica`` is what the generation
fleet's Supervisor spawns per replica rank: it loads one model, wraps
it in a :class:`~paddle1_tpu.serving.GenerationServer` (continuous
batching, paged KV, deadlines — the PR 16 stack), binds a loopback
socket, publishes its endpoint, and serves framed requests from the
fleet dispatcher until a drain is requested.

The same three load-bearing ordering rules as :mod:`.replica` apply
(beat first so ``PADDLE_FT_*`` never leaks to grandchildren; chaos
arms in incarnation 0 only; the endpoint file is written AFTER the
server started, so publishing the port IS the ready signal).

What is new here is the token plane: a ``generate`` frame opens a
long-lived stream, and a per-stream **pump thread** walks the
:class:`TokenStream`, sending one ``tokens`` frame per produced token
with a monotone absolute sequence number (``seq`` starts at the
resume count for replayed streams — the client already holds the
replayed tokens, so this replica never re-sends them). The fleet's
dedup key is that sequence number; this end's only job is to keep it
exact. A ``stream_end`` frame carries the finish reason and, for
typed failures, the error type/message so the fleet can decide
between failover and surfacing.

Chaos fires per TOKEN FRAME (``check_gen_replica``): a kill point
SIGKILLs the process mid-stream (the fleet must fail over every
in-flight stream bit-identically); a hang point wedges the token
plane process-wide — pumps stop sending while the main thread keeps
heartbeating, so only the fleet's stream-silence deadline can catch
it (heartbeats alone are blind to a wedged stream).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import sys
import threading
import time
from typing import Dict, Optional

import numpy as np

from .replica import _write_endpoint, load_model

__all__ = ["main"]

# process-wide wedge latch (chaos GEN_REPLICA_HANG): once set, every
# pump thread stops sending token frames forever while the main thread
# keeps beating — the wedged-stream failure mode the fleet's transport
# deadline exists to catch
_WEDGE = threading.Event()


class _DrainRequested(Exception):
    """Internal: aborts a blocking frame read when a drain arrived."""


def _pump_stream(conn: socket.socket, send_lock: threading.Lock,
                 stream, stream_id: int, resume_n: int, rank: int,
                 streams: Dict[int, object],
                 streams_lock: threading.Lock, core_chaos) -> None:
    """Walk one TokenStream, relaying tokens as wire frames.

    ``seq`` is the absolute token index within the stream: the first
    frame of a resumed stream carries ``seq == resume_n`` (the client
    kept tokens 0..resume_n-1 across the failover — re-sending them
    would only exercise the dedup path for nothing).
    """
    from . import wire
    seq = int(resume_n)
    exc: Optional[BaseException] = None
    try:
        for tok in stream:
            if core_chaos.enabled():
                point = core_chaos.check_gen_replica(rank)
                if point == core_chaos.GEN_REPLICA_KILL:
                    # ungraceful death mid-stream: no stream_end, no
                    # cleanup — the fleet must replay every in-flight
                    # stream on a survivor, bit-identically
                    os.kill(os.getpid(), signal.SIGKILL)
                elif point == core_chaos.GEN_REPLICA_HANG:
                    _WEDGE.set()
            if _WEDGE.is_set():
                # wedged token plane: heartbeats keep flowing (main
                # thread), tokens don't — park this pump forever (the
                # latch only ever goes up, so wait on one that can't)
                threading.Event().wait()  # pragma: no cover - never returns
            try:
                with send_lock:
                    wire.send_stream_tokens(  # noqa: lock-blocking — lock is FOR sendall
                        conn, stream_id, seq, [tok])
            except (OSError, ConnectionError):
                # fleet connection died mid-stream: stop decoding what
                # nobody can read — the failover replays it elsewhere
                stream.cancel()
                return
            seq += 1
    except BaseException as e:  # noqa: broad-except — typed stream
        # failures (deadline/budget/errors) close the stream on the
        # wire with their type so the fleet can route them
        exc = e
    finally:
        with streams_lock:
            streams.pop(stream_id, None)
    reason = stream.finish_reason or ("error" if exc is not None
                                      else "length")
    try:
        with send_lock:
            wire.send_stream_end(  # noqa: lock-blocking — lock is FOR sendall
                conn, stream_id, seq, reason,
                etype=type(exc).__name__ if exc is not None else None,
                msg=str(exc) if exc is not None else "")
    except (OSError, ConnectionError):
        pass  # fleet gone; its failover owns the stream now


def _pong_payload(srv, args, core_health) -> Dict[str, object]:
    eng = srv.engine
    loop = srv._loop
    out = {
        "version": args.version, "rank": args.rank,
        "incarnation": core_health.incarnation(),
        "slots": eng.slots,
        "decode_compiles": eng.decode_compile_count,
        "parked": len(loop._parked) if loop is not None else 0,
    }
    if eng.paged:
        out["pool"] = eng.pool.stats()
    return out


def _serve_conn(conn: socket.socket, srv, args, core_chaos,
                core_health) -> None:
    """Pump one fleet connection until EOF or drain."""
    from . import wire
    conn.settimeout(0.25)
    send_lock = threading.Lock()
    streams: Dict[int, object] = {}        # stream id -> TokenStream
    streams_lock = threading.Lock()

    def idle():
        core_health.beat()
        if core_health.drain_requested():
            raise _DrainRequested

    while True:
        try:
            header, arrays = wire.recv_msg(conn, idle=idle)
        except (ConnectionError, OSError):
            # fleet connection lost: cancel every stream it was
            # reading — this replica must not burn slots decoding
            # tokens nobody will consume (the fleet replays them)
            with streams_lock:
                live = list(streams.values())
                streams.clear()
            for st in live:
                st.cancel()
            return
        kind = header.get("kind")
        rid = header.get("id")
        if kind == "ping":
            payload = {"kind": "pong", "id": rid}
            payload.update(_pong_payload(srv, args, core_health))
            with send_lock:
                wire.send_msg(conn, payload)  # noqa: lock-blocking — frame lock IS for sendall
        elif kind == "metrics":
            with send_lock:
                wire.send_msg(conn, {  # noqa: lock-blocking — frame lock IS for sendall
                    "kind": "metrics_result", "id": rid,
                    "version": args.version,
                    "snapshot": srv.metrics.snapshot()})
        elif kind == "cancel":
            with streams_lock:
                st = streams.get(int(header.get("stream", -1)))
            if st is not None:
                st.cancel()
        elif kind == "generate":
            full = np.asarray(arrays[0], np.int64).reshape(-1)
            n_resume = int(header.get("resume", 0))
            prompt = full[:full.size - n_resume] if n_resume else full
            resume = full[full.size - n_resume:] if n_resume else None
            if resume is not None and resume.size:
                # a replay whose tail already finished the stream (the
                # old replica died between its final token frame and
                # the stream_end): close it on the wire, don't decode
                eos = srv.engine.eos_id
                done_reason = None
                if eos is not None and int(resume[-1]) == eos:
                    done_reason = "eos"
                elif resume.size >= int(header.get("max_new") or 0):
                    done_reason = "length"
                if done_reason is not None:
                    with send_lock:
                        wire.send_stream_end(  # noqa: lock-blocking — frame lock IS for sendall
                            conn, int(rid), n_resume, done_reason)
                    continue
            try:
                stream = srv.submit(
                    prompt,
                    max_new_tokens=header.get("max_new"),
                    temperature=float(header.get("temperature", 0.0)),
                    top_k=int(header.get("top_k", 0)),
                    seed=header.get("seed"),
                    deadline_ms=header.get("deadline_ms"),
                    priority=int(header.get("priority", 0)),
                    resume_tokens=resume)
            except Exception as e:  # noqa: broad-except — admission
                # errors (shed/closed/invalid) end the stream typed so
                # the fleet can retry elsewhere or surface them
                with send_lock:
                    wire.send_stream_end(  # noqa: lock-blocking — frame lock IS for sendall
                        conn, int(rid), n_resume, "error",
                        etype=type(e).__name__, msg=str(e))
                continue
            with streams_lock:
                streams[int(rid)] = stream
            t = threading.Thread(
                target=_pump_stream,
                args=(conn, send_lock, stream, int(rid), n_resume,
                      args.rank, streams, streams_lock, core_chaos),
                daemon=True, name=f"p1t-genpump-{rid}")
            t.start()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="paddle1_tpu generation replica worker")
    ap.add_argument("--endpoint-file", required=True)
    ap.add_argument("--model", required=True,
                    help="'file.py:factory', 'module:factory', or "
                         "'artifact:/path'")
    ap.add_argument("--model-arg", default="")
    ap.add_argument("--version", default="v0")
    ap.add_argument("--rank", type=int, default=0)
    ap.add_argument("--chaos", default="",
                    help="chaos spec armed in THIS process "
                         "(incarnation 0 only)")
    ap.add_argument("--gen-config", default="{}",
                    help="JSON kwargs split between GenerationEngine "
                         "and GenerationServer")
    args = ap.parse_args(argv)

    from ..core import chaos as core_chaos
    from ..core import health as core_health

    # 1. adopt the heartbeat channel (pops PADDLE_FT_* before anything
    #    else can snapshot the env for grandchildren)
    core_health.beat()
    # 2. chaos replays clean in restarted lives
    if args.chaos and core_health.incarnation() == 0:
        core_chaos.configure(args.chaos)

    from .generate import GenerationEngine, GenerationServer

    model = load_model(args.model, args.model_arg)
    cfg = json.loads(args.gen_config or "{}")
    eng_keys = ("slots", "max_seq", "prefill_buckets", "eos_id",
                "cache_dtype", "paged", "page_size", "pages",
                "prefix_cache", "spec_tokens", "int8")
    eng_cfg = {k: cfg[k] for k in eng_keys if k in cfg}
    srv_cfg = {k: v for k, v in cfg.items() if k not in eng_keys}
    engine = GenerationEngine(model, **eng_cfg)
    srv = GenerationServer(engine, **srv_cfg)
    srv.start()

    lst = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lst.bind(("127.0.0.1", 0))
    lst.listen(4)
    lst.settimeout(0.25)
    port = lst.getsockname()[1]
    # 3. publishing the endpoint IS the ready signal: the server (and
    #    its one compiled decode signature, when warmup is on) exists
    #    before the fleet can route a stream here
    _write_endpoint(args.endpoint_file, {
        "port": port, "pid": os.getpid(), "rank": args.rank,
        "version": args.version,
        "incarnation": core_health.incarnation()})
    print(f"genreplica rank={args.rank} version={args.version} "
          f"serving on 127.0.0.1:{port}", flush=True)

    try:
        while not core_health.drain_requested():
            core_health.beat()
            try:
                conn, _ = lst.accept()
            except socket.timeout:
                continue
            try:
                _serve_conn(conn, srv, args, core_chaos, core_health)
            except _DrainRequested:
                break
    finally:
        lst.close()
    # graceful drain: finish every accepted stream (or fail it typed),
    # then prove the token/page ledgers balance — a replica that leaks
    # a stream or a KV page exits 3 and the fleet treats it as failed
    report = srv.drain()
    print(f"genreplica rank={args.rank} drained: "
          f"{json.dumps({k: v for k, v in report.items() if k != 'prefill_compile_counts'})}",
          flush=True)
    clean = (report["unaccounted"] == 0
             and report.get("kv_pages_owed", 0) == 0)
    return 0 if clean else 3


if __name__ == "__main__":
    sys.exit(main())
