"""Typed serving errors (extending the core enforce hierarchy): callers
distinguish *shed* (back off / retry elsewhere), *expired* (the answer
is late, not wrong) and *closed* (the worker is draining) without
string-matching messages — the load-balancer contract."""

from __future__ import annotations

from ..core.errors import (ExecutionTimeoutError, PreconditionNotMetError,
                           ResourceExhaustedError, UnavailableError)

__all__ = ["ServerOverloaded", "DeadlineExceeded", "ServerClosed",
           "ReplicaFailed", "DeployFailed", "ScaleFailed", "SlotWedged",
           "StreamCancelled", "KVPoolExhausted", "StreamFailed",
           "KVPageAccountingError"]


class ServerOverloaded(ResourceExhaustedError):
    """Admission control shed the request: the bounded queue is full
    (or, at the fleet front end, adaptive admission shed it under
    sustained overload). Raised synchronously by ``submit`` — nothing
    was enqueued."""


class DeadlineExceeded(ExecutionTimeoutError):
    """The request's deadline expired while it was still queued (it was
    never dispatched — delivered through the request's future), or a
    reader's ``result(timeout=...)`` ran out while the future was still
    unresolved (the request itself may yet complete; first-wins
    resolution keeps the accounting straight either way)."""


class ServerClosed(UnavailableError):
    """The server is draining or stopped and no longer admits work."""


class ReplicaFailed(UnavailableError):
    """Every failover retry for this request exhausted: the replica
    holding it died or wedged, and ``serve_retry_max`` re-dispatches
    onto other replicas failed too (or none were healthy). Delivered
    through the request's future — the client-visible form of a fleet
    that genuinely could not serve this request."""


class DeployFailed(PreconditionNotMetError):
    """A model hot-swap's canary replica never became healthy (spawn
    failure, ready-handshake timeout, or a failed canary inference);
    the deploy was rolled back and the fleet keeps serving the old
    version."""


class ScaleFailed(PreconditionNotMetError):
    """A ``scale_to`` transition could not complete: a scale-out
    replica never became healthy within the ready window (the corpse
    was retired; replicas that DID come up stay in rotation — capacity
    is kept, the shortfall is typed), or the fleet was not in a state
    to scale. The fleet keeps serving at whatever size it actually
    reached — an autoscaler backs off and re-evaluates instead of
    flapping."""


class SlotWedged(UnavailableError):
    """One decode slot of the generation engine wedged mid-stream (the
    ``gen_slot_wedge`` chaos point's model of a poisoned request):
    ONLY that request's TokenStream fails — delivered through the
    stream, tokens already streamed stay valid — and the slot is
    released; cohabiting sequences in the continuous batch are
    untouched."""


class KVPoolExhausted(ResourceExhaustedError):
    """The paged KV pool (``serve_gen_kv_pages``) has no free page even
    after evicting every evictable cached prefix: the live sequences'
    tokens genuinely exceed pool capacity. Raised at prefill admission
    (the request never claimed a slot) or delivered mid-stream through
    the starved request's TokenStream when a decode-time page fault
    cannot be served — cohabiting slots keep decoding. Remedies: more
    pages, shorter max_new_tokens, fewer slots, or a bigger prefix
    cache hit rate (shared prompts)."""


class StreamFailed(UnavailableError):
    """Every failover retry for this token stream exhausted: the
    replica decoding it died or wedged mid-stream, and re-admitting
    ``prompt + tokens already emitted`` onto ``serve_retry_max``
    survivors failed too (or none were healthy). Delivered through the
    stream — tokens already delivered stay valid and exactly-once; this
    is the generative analog of :class:`ReplicaFailed` and the ONLY
    client-visible form of replica loss (a successful failover is
    invisible: the continuation is bit-identical)."""


class KVPageAccountingError(PreconditionNotMetError):
    """KV page refcount accounting went inconsistent: a page was
    released more times than it was held (double release), or the
    debug invariant checker (``FLAGS_debug_kv_refcount``) found the
    refcounts out of sync with the free list / registered holders.
    Raised typed BEFORE the free list can be corrupted — a double-freed
    page handed to two slots would silently cross-write their KV."""


class StreamCancelled(UnavailableError):
    """The client cancelled its TokenStream: the slot was released at
    the next step boundary and no further tokens stream. Reading
    ``result()`` on a cancelled stream raises this (iteration just
    stops) — the cancel is client-initiated, so it counts as accounted,
    not as a server failure."""
