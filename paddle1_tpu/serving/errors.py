"""Typed serving errors (extending the core enforce hierarchy): callers
distinguish *shed* (back off / retry elsewhere), *expired* (the answer
is late, not wrong) and *closed* (the worker is draining) without
string-matching messages — the load-balancer contract."""

from __future__ import annotations

from ..core.errors import (ExecutionTimeoutError, PreconditionNotMetError,
                           ResourceExhaustedError, UnavailableError)

__all__ = ["ServerOverloaded", "DeadlineExceeded", "ServerClosed",
           "ReplicaFailed", "DeployFailed"]


class ServerOverloaded(ResourceExhaustedError):
    """Admission control shed the request: the bounded queue is full
    (or, at the fleet front end, adaptive admission shed it under
    sustained overload). Raised synchronously by ``submit`` — nothing
    was enqueued."""


class DeadlineExceeded(ExecutionTimeoutError):
    """The request's deadline expired while it was still queued (it was
    never dispatched — delivered through the request's future), or a
    reader's ``result(timeout=...)`` ran out while the future was still
    unresolved (the request itself may yet complete; first-wins
    resolution keeps the accounting straight either way)."""


class ServerClosed(UnavailableError):
    """The server is draining or stopped and no longer admits work."""


class ReplicaFailed(UnavailableError):
    """Every failover retry for this request exhausted: the replica
    holding it died or wedged, and ``serve_retry_max`` re-dispatches
    onto other replicas failed too (or none were healthy). Delivered
    through the request's future — the client-visible form of a fleet
    that genuinely could not serve this request."""


class DeployFailed(PreconditionNotMetError):
    """A model hot-swap's canary replica never became healthy (spawn
    failure, ready-handshake timeout, or a failed canary inference);
    the deploy was rolled back and the fleet keeps serving the old
    version."""
