"""Typed serving errors (extending the core enforce hierarchy): callers
distinguish *shed* (back off / retry elsewhere), *expired* (the answer
is late, not wrong) and *closed* (the worker is draining) without
string-matching messages — the load-balancer contract."""

from __future__ import annotations

from ..core.errors import (ExecutionTimeoutError, ResourceExhaustedError,
                           UnavailableError)

__all__ = ["ServerOverloaded", "DeadlineExceeded", "ServerClosed"]


class ServerOverloaded(ResourceExhaustedError):
    """Admission control shed the request: the bounded queue is full.
    Raised synchronously by ``Server.submit`` — nothing was enqueued."""


class DeadlineExceeded(ExecutionTimeoutError):
    """The request's deadline expired while it was still queued; it was
    never dispatched. Delivered through the request's future."""


class ServerClosed(UnavailableError):
    """The server is draining or stopped and no longer admits work."""
