"""Open-loop production-day traffic simulator (ISSUE 18).

The proof harness for the autoscaler: a deterministic load generator
that composes the request patterns a real serving day throws at a
fleet — diurnal ramps, flash crowds, heavy-tail payload sizes, mixed
priority classes — and offers them OPEN-LOOP (arrival times are drawn
up front from the model, never modulated by completions: a saturated
fleet keeps getting offered load, exactly the regime closed-loop
benchmarks hide).

The model is declarative (:class:`TrafficModel`, flag grammar in
:func:`parse_traffic`), the schedule is a pure function of the model
(:func:`schedule` — same seed, same day), and the runner
(:func:`run`) drives any ``submit(arrival) -> future`` callable,
counting typed sheds as accounted outcomes and collecting e2e
latencies off the submit thread so admission never blocks on
completions. Chaos composes from the outside: arm the existing
``replica_kill`` / ``gen_slot_wedge`` / ``gen_page_pressure`` points
and the same schedule replays against a failing fleet.

Arrival times use inhomogeneous-Poisson thinning: draw homogeneous
arrivals at the model's peak rate, keep each with probability
``rate(t)/peak`` — exact for any bounded rate curve, and determinism
rides one ``random.Random(seed)``.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import queue
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..core import flags as core_flags
from ..core.errors import InvalidArgumentError
from .errors import ServerOverloaded

__all__ = ["FlashCrowd", "TrafficModel", "Arrival", "parse_traffic",
           "schedule", "run"]


@dataclasses.dataclass(frozen=True)
class FlashCrowd:
    """One multiplicative burst: ``rate *= multiplier`` for
    ``[start_s, start_s + duration_s)``."""
    start_s: float
    duration_s: float
    multiplier: float


@dataclasses.dataclass(frozen=True)
class TrafficModel:
    """One production day, compressed. ``diurnal`` is the sinusoid
    amplitude as a fraction of ``rps`` (one full period over
    ``duration_s`` — troughs at the ends, peak mid-day); payload
    lengths are Pareto(``tail_alpha``) on ``[len_min, len_max]`` (the
    heavy tail: most requests small, a few huge); ``priorities`` are
    ``(class, weight)`` sampling weights."""
    rps: float = 20.0
    duration_s: float = 30.0
    diurnal: float = 0.0
    flash: Tuple[FlashCrowd, ...] = ()
    tail_alpha: float = 1.5
    len_min: int = 8
    len_max: int = 512
    priorities: Tuple[Tuple[int, float], ...] = ((0, 1.0),)
    deadline_ms: Optional[float] = None
    seed: int = 0

    def __post_init__(self):
        if self.rps <= 0 or self.duration_s <= 0:
            raise InvalidArgumentError(
                "traffic needs rps > 0 and dur > 0")
        if not 0 <= self.diurnal < 1:
            raise InvalidArgumentError(
                f"diurnal amplitude must be in [0, 1), got "
                f"{self.diurnal} (a full-amplitude trough is zero "
                "traffic — model that as duration instead)")
        if self.tail_alpha <= 0:
            raise InvalidArgumentError("tail alpha must be > 0")
        if not 1 <= self.len_min <= self.len_max:
            raise InvalidArgumentError(
                f"need 1 <= len_min <= len_max, got "
                f"[{self.len_min}, {self.len_max}]")
        if not self.priorities or \
                any(w <= 0 for _, w in self.priorities):
            raise InvalidArgumentError(
                "priorities need >= 1 class with positive weight")
        for fc in self.flash:
            if fc.duration_s <= 0 or fc.multiplier <= 0:
                raise InvalidArgumentError(
                    f"bad flash crowd {fc} — needs positive duration "
                    "and multiplier")

    def rate_at(self, t: float) -> float:
        """Offered rate (req/s) at second ``t`` of the day."""
        r = self.rps * (1.0 + self.diurnal * math.sin(
            2.0 * math.pi * t / self.duration_s))
        for fc in self.flash:
            if fc.start_s <= t < fc.start_s + fc.duration_s:
                r *= fc.multiplier
        return r

    def peak_rate(self) -> float:
        base = self.rps * (1.0 + self.diurnal)
        mult = 1.0
        for fc in self.flash:
            mult = max(mult, fc.multiplier)
        return base * mult


@dataclasses.dataclass(frozen=True)
class Arrival:
    """One scheduled request: when, how big, how urgent."""
    t: float                       # seconds from run start
    priority: int
    length: int                    # payload rows / prompt tokens
    deadline_ms: Optional[float]


# -- the flag grammar -------------------------------------------------------

_GRAMMAR = ("'rps=40;dur=30;diurnal=0.3;flash=10x@12+6[,8x@20+2];"
            "tail=1.5;len=8:512;prio=0:0.7,1:0.2,2:0.1;deadline=250;"
            "seed=7' — every key optional")


def _parse_flash(val: str) -> Tuple[FlashCrowd, ...]:
    crowds = []
    for part in val.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            mult, _, rest = part.partition("x@")
            start, _, dur = rest.partition("+")
            crowds.append(FlashCrowd(start_s=float(start),
                                     duration_s=float(dur),
                                     multiplier=float(mult)))
        except ValueError:
            raise InvalidArgumentError(
                f"bad flash clause {part!r} — grammar "
                "'<mult>x@<start>+<dur>'") from None
    return tuple(crowds)


def _parse_prio(val: str) -> Tuple[Tuple[int, float], ...]:
    out = []
    for part in val.split(","):
        part = part.strip()
        if not part:
            continue
        cls, sep, w = part.partition(":")
        try:
            out.append((int(cls), float(w) if sep else 1.0))
        except ValueError:
            raise InvalidArgumentError(
                f"bad priority clause {part!r} — grammar "
                "'<class>:<weight>'") from None
    return tuple(out)


def parse_traffic(spec: Optional[str] = None) -> TrafficModel:
    """Parse the ``serve_traffic`` flag grammar into a
    :class:`TrafficModel`; unknown keys and unparsable values are
    typed errors naming the clause."""
    if spec is None:
        spec = core_flags.flag("serve_traffic")
    kw: Dict[str, object] = {}
    for clause in str(spec).split(";"):
        clause = clause.strip()
        if not clause:
            continue
        key, sep, val = clause.partition("=")
        key, val = key.strip(), val.strip()
        if not sep:
            raise InvalidArgumentError(
                f"bad traffic clause {clause!r} — grammar: {_GRAMMAR}")
        try:
            if key == "rps":
                kw["rps"] = float(val)
            elif key == "dur":
                kw["duration_s"] = float(val)
            elif key == "diurnal":
                kw["diurnal"] = float(val)
            elif key == "flash":
                kw["flash"] = _parse_flash(val)
            elif key == "tail":
                kw["tail_alpha"] = float(val)
            elif key == "len":
                lo, _, hi = val.partition(":")
                kw["len_min"], kw["len_max"] = int(lo), int(hi or lo)
            elif key == "prio":
                kw["priorities"] = _parse_prio(val)
            elif key == "deadline":
                kw["deadline_ms"] = float(val) if float(val) > 0 \
                    else None
            elif key == "seed":
                kw["seed"] = int(val)
            else:
                raise InvalidArgumentError(
                    f"unknown traffic key {key!r} — grammar: "
                    f"{_GRAMMAR}")
        except ValueError:
            raise InvalidArgumentError(
                f"bad traffic value in {clause!r} — grammar: "
                f"{_GRAMMAR}") from None
    return TrafficModel(**kw)


# -- the schedule -----------------------------------------------------------

def schedule(model: TrafficModel) -> List[Arrival]:
    """The whole day's arrivals, up front (pure in the model — same
    seed, same day, so a chaos replay sees the identical offered
    load). Inhomogeneous-Poisson thinning at the model's peak rate."""
    rng = random.Random(model.seed)
    peak = model.peak_rate()
    arrivals: List[Arrival] = []
    classes = [c for c, _ in model.priorities]
    weights = [w for _, w in model.priorities]
    t = 0.0
    while True:
        t += rng.expovariate(peak)
        if t >= model.duration_s:
            break
        if rng.random() > model.rate_at(t) / peak:
            continue  # thinned: the instantaneous rate is below peak
        # Pareto(alpha) scaled to len_min, truncated to len_max — the
        # heavy tail that makes padding/batching earn its keep
        length = min(model.len_max,
                     int(model.len_min * rng.paretovariate(
                         model.tail_alpha)))
        arrivals.append(Arrival(
            t=t, priority=rng.choices(classes, weights)[0],
            length=length, deadline_ms=model.deadline_ms))
    return arrivals


# -- the runner -------------------------------------------------------------

def run(arrivals: Sequence[Arrival],
        submit: Callable[[Arrival], object],
        collectors: int = 16,
        result_timeout_s: float = 60.0,
        speed: float = 1.0,
        on_tick: Optional[Callable[[float], None]] = None,
        tick_s: float = 0.5) -> dict:
    """Offer ``arrivals`` open-loop against wall clock: each is
    submitted at ``t / speed`` seconds from start whether or not
    earlier requests completed. ``submit`` returns a future-like
    (``result(timeout)``) or raises — :class:`ServerOverloaded` counts
    as a typed shed (accounted back-pressure, not a failure), any
    other synchronous raise as a submit failure. Completion latencies
    are collected by a pool off the submit thread. ``on_tick(now_s)``
    (when given) fires about every ``tick_s`` of run time — the
    replica-hours integrator's hook.

    Returns ``{offered, admitted, shed, submit_failed, completed,
    errors, lateness_p99_ms, latency_ms: {p50, p95, p99, n},
    error_types}`` where ``offered == admitted + shed +
    submit_failed`` and ``admitted == completed + errors`` — the
    open-loop accounting identity.
    """
    results: collections.deque = collections.deque()  # thread-safe appends
    pending: "queue.Queue" = queue.Queue()
    stop = object()

    def _collect():
        while True:
            item = pending.get()
            if item is stop:
                return
            t_sub, fut = item
            try:
                fut.result(timeout=result_timeout_s)
                results.append(("ok", (time.monotonic() - t_sub) * 1e3,
                                None))
            except Exception as e:  # noqa: broad-except — EVERY typed
                # completion failure (deadline, shed-on-retry, replica
                # loss) is one accounted outcome; classification
                # happens below by type name
                results.append(("err", (time.monotonic() - t_sub) * 1e3,
                                type(e).__name__))

    pool = [threading.Thread(target=_collect, daemon=True,
                             name=f"p1t-traffic-collect-{i}")
            for i in range(max(1, int(collectors)))]
    for th in pool:
        th.start()

    offered = admitted = shed = submit_failed = 0
    lateness_ms: List[float] = []
    t0 = time.monotonic()
    next_tick = 0.0
    for a in arrivals:
        due = t0 + a.t / speed
        now = time.monotonic()
        if on_tick is not None:
            while next_tick <= now - t0:
                on_tick(next_tick)
                next_tick += tick_s
        if due > now:
            time.sleep(due - now)
        lateness_ms.append(max(0.0, (time.monotonic() - due) * 1e3))
        offered += 1
        try:
            fut = submit(a)
            admitted += 1
            pending.put((time.monotonic(), fut))
        except ServerOverloaded:
            shed += 1          # typed back-pressure: accounted, legal
        except Exception:  # noqa: broad-except — an open-loop run
            # keeps offering through a failing fleet; the failure is
            # counted and the gate decides what it means
            submit_failed += 1
    for _ in pool:
        pending.put(stop)
    for th in pool:
        th.join(timeout=result_timeout_s + 10.0)

    oks = sorted(ms for kind, ms, _ in results if kind == "ok")
    errs = [etype for kind, _, etype in results if kind == "err"]

    def _pct(xs: List[float], q: float) -> Optional[float]:
        if not xs:
            return None
        return xs[min(len(xs) - 1, int(q * len(xs)))]

    lateness_ms.sort()
    return {
        "offered": offered,
        "admitted": admitted,
        "shed": shed,
        "submit_failed": submit_failed,
        "completed": len(oks),
        "errors": len(errs),
        "error_types": dict(collections.Counter(errs)),
        # open-loop fidelity: how late the generator itself ran (a
        # blocked submit path shows up here, not as hidden pacing)
        "lateness_p99_ms": round(_pct(lateness_ms, 0.99) or 0.0, 2),
        "latency_ms": {
            "p50": round(_pct(oks, 0.50) or 0.0, 2),
            "p95": round(_pct(oks, 0.95) or 0.0, 2),
            "p99": round(_pct(oks, 0.99) or 0.0, 2),
            "n": len(oks),
        },
    }
