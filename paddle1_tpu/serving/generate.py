"""Generative serving: device-resident KV-cache decode with slot-based
continuous batching and per-token streaming (ISSUE 9 / ROADMAP item 2).

The eager decode stack (``nn.decode.dynamic_decode``) pays one host
round trip — and, through the concat-based
``MultiHeadAttention.Cache``, one growing-shape retrace — per token per
sequence. This module is the serving analog of PR 1's ``step_many``:
the whole autoregressive loop stays on device and ONE jitted dispatch
per token advances every active sequence, however many there are and
whenever each arrived.

Design (Orca's iteration-level continuous batching + vLLM's
preallocated KV management, adapted to a bucketed-XLA world where
shapes must stay static):

* **Prefill/decode split.** Prefill — the whole prompt in one causal
  pass — is compiled once per *prompt-length bucket*
  (``serve_gen_prefill_buckets``, resolved through the same
  ``resolve_buckets`` policy as the batch buckets). Decode is compiled
  exactly ONCE: its signature is pinned to the fixed
  ``[slots, max_seq]`` cache, so ragged arrivals, ragged prompt
  lengths, and any active-slot pattern reuse the same executable (the
  ``decode_compile_count`` trace counter is the acceptance gate).
* **Device-resident slot cache.** Per layer, preallocated
  ``[slots, max_seq, heads, dim]`` K/V arrays
  (:meth:`~paddle1_tpu.nn.MultiHeadAttention.gen_slot_cache`) written
  in place at a per-slot cursor via ``dynamic_update_slice`` and
  DONATED through every dispatch — no per-token cache copy, no
  per-token reshape, no retrace.
* **Slot-based continuous batching.** New requests claim free slots in
  the running decode batch between steps, as finished ones release
  theirs; a slot's rows are never read by any other slot (per-row
  writes + per-slot causal masks), so cohabiting sequences are
  bit-identical to an uncontended run — the isolation contract the
  ``gen_slot_wedge`` chaos test pins.
* **Sampling on device.** Greedy/temperature/top-k (the shared
  ``nn.decode.sample_logits_array`` op) run *inside* the jitted step
  with per-slot RNG keys (carried as raw key data, split per token),
  so sampled decode is still one dispatch and a request's draws depend
  only on (its seed, its token index) — never on its slot or its
  neighbors.
* **Per-token streaming.** Each request gets a :class:`TokenStream`
  (iterator + ``cancel()``); a bounded per-stream buffer is the
  backpressure (the ``core/async_loss`` bounded-window idiom): a
  client that stops consuming parks its slot instead of growing host
  memory. Admission/deadline/shed/drain follow the PR 4 Server
  contracts, with the accounting extended to tokens:
  ``tokens_generated == tokens_streamed + tokens_dropped`` and
  request-level ``unaccounted == 0`` in every drain report.

Quickstart::

    lm = CausalLM(vocab_size=32000, d_model=512, nhead=8,
                  num_layers=12, max_seq=512)
    srv = GenerationServer(lm, slots=16, max_seq=512, eos_id=2).start()
    stream = srv.submit(prompt_ids, max_new_tokens=128, temperature=0.8,
                        top_k=40, seed=7)
    for tok in stream:          # per-token, as they decode
        print(tok)
    srv.drain()                 # unaccounted == 0, tokens_owed == 0
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core import chaos as core_chaos
from ..core import flags as core_flags
from ..core import health as core_health
from ..core import jit_sanitizer
from ..core import locks
from ..core.errors import InvalidArgumentError
from .engine import resolve_buckets
from ..obs import events as obs_events
from .errors import (DeadlineExceeded, KVPoolExhausted, ServerClosed,
                     ServerOverloaded, SlotWedged, StreamCancelled)
from .metrics import ServingMetrics
from .paging import PARKING_PAGE, PagePool
from .speculate import NGramSpeculator

__all__ = ["CausalLM", "GenerationEngine", "GenerationServer",
           "TokenStream"]


# ---------------------------------------------------------------------------
# reference model

from ..nn.layer_base import Layer as _Layer  # noqa: E402  (nn loads
# before serving in the package __init__, and nn never imports serving)


class CausalLM(_Layer):
    """Small decoder-only transformer LM built from the repo's own
    blocks — the generation engine's reference model (tests/bench serve
    it; users serve any Layer implementing the same contract:
    ``gen_slot_cache(slots, max_seq)`` plus
    ``forward(ids, cache=, positions=, attn_mask=)`` returning
    ``(logits, new_cache)`` when a cache is passed).

    Supports BOTH cache disciplines: the serving
    :attr:`~paddle1_tpu.nn.MultiHeadAttention.GenCache` slot path and
    the eager concat-based ``Cache`` path (``empty_cache``), so the
    same weights drive the engine and the ``dynamic_decode`` baseline.
    """

    def __init__(self, vocab_size, d_model=64, nhead=4,
                 dim_feedforward=128, num_layers=2, max_seq=256):
        super().__init__()
        from .. import nn
        self.vocab_size = int(vocab_size)
        self.max_seq = int(max_seq)
        self.embed = nn.Embedding(self.vocab_size, d_model)
        self.pos_embed = nn.Embedding(self.max_seq, d_model)
        layer = nn.TransformerEncoderLayer(
            d_model, nhead, dim_feedforward, dropout=0.0)
        self.encoder = nn.TransformerEncoder(layer, num_layers)
        self.head = nn.Linear(d_model, self.vocab_size)

    def gen_slot_cache(self, slots, max_seq, dtype="float32"):
        return self.encoder.gen_slot_cache(slots, max_seq, dtype)

    def gen_paged_cache(self, pages, page_size, dtype="float32"):
        return self.encoder.gen_paged_cache(pages, page_size, dtype)

    def empty_cache(self, batch):
        """Eager incremental-decode cache (the concat-based ``Cache``
        path ``dynamic_decode`` drives)."""
        from ..core.tensor import to_tensor
        return self.encoder.gen_cache(
            to_tensor(np.zeros((int(batch), 1), np.float32)))

    def forward(self, ids, cache=None, positions=None, attn_mask=None):
        from ..core.tensor import to_tensor
        from ..nn import MultiHeadAttention
        B, L = ids.shape[0], ids.shape[1]
        off = 0
        if cache is not None and isinstance(
                cache[0], MultiHeadAttention.Cache):
            off = cache[0].k.shape[1]
        if positions is None:
            positions = to_tensor(np.broadcast_to(
                np.arange(off, off + L, dtype=np.int64), (B, L)).copy())
        x = self.embed(ids) + self.pos_embed(positions)
        if (cache is not None and len(cache) and
                isinstance(cache[0], MultiHeadAttention.PagedCache)):
            # paged decode: masking derives from the page table +
            # cursor inside paged_attention — never build a mask here
            pass
        elif attn_mask is None and L > 1:
            # causal over the (cached + new) key length: needed for any
            # multi-query pass — the no-cache forward AND the eager
            # concat-cache prefill (single-query decode needs none)
            j = np.arange(off + L)[None, :]
            i = np.arange(L)[:, None]
            attn_mask = to_tensor((j <= off + i)[None, None])
        out = self.encoder(x, attn_mask, cache)
        if cache is None:
            return self.head(out)
        h, new_caches = out
        return self.head(h), new_caches


# ---------------------------------------------------------------------------
# token stream


class TokenStream:
    """Per-request streaming handle: iterate tokens as they decode.

    The engine side ``_put``s tokens and ``_finish``es the stream
    (first-wins, like :class:`~paddle1_tpu.serving.batcher.ServeFuture`);
    the client iterates (``for tok in stream``), collects
    (``result()``), or ``cancel()``s. The buffer of *unconsumed* tokens
    is bounded (``serve_gen_stream_buffer``): when full, the engine
    parks the slot — decode for this request pauses, the device batch
    keeps serving everyone else — until the client drains it.

    ``finish_reason``: ``"eos"`` | ``"length"`` (requested
    ``max_new_tokens`` reached) | ``"deadline"`` | ``"budget"`` (server
    token budget cut the stream short — typed) | ``"cancelled"`` |
    ``"error"`` (incl. a drain that ran out of patience — the typed
    exception says which).
    """

    def __init__(self, buffer_cap: int):
        self._cond = threading.Condition()
        self._cap = int(buffer_cap)
        self._buf: collections.deque = collections.deque()
        self._all: List[int] = []
        self._done = False
        self._exc: Optional[BaseException] = None
        self._cancel_requested = False
        self.finish_reason: Optional[str] = None

    # -- engine side --------------------------------------------------------

    def _writable(self) -> bool:
        return len(self._buf) < self._cap

    def _put(self, tok: int) -> bool:
        with self._cond:
            if self._done:
                return False
            self._buf.append(int(tok))
            self._all.append(int(tok))
            self._cond.notify_all()
        return True

    def _finish(self, reason: str,
                exc: Optional[BaseException] = None) -> bool:
        with self._cond:
            if self._done:
                return False
            self._done = True
            self.finish_reason = reason
            self._exc = exc
            self._cond.notify_all()
        return True

    # -- client side --------------------------------------------------------

    def cancel(self) -> None:
        """Ask the engine to release this request's slot at the next
        step boundary; no further tokens stream. Idempotent; a stream
        that already finished is untouched."""
        with self._cond:
            self._cancel_requested = True
            self._cond.notify_all()

    def done(self) -> bool:
        return self._done

    @property
    def tokens(self) -> List[int]:
        """Every token streamed so far (a snapshot copy)."""
        with self._cond:
            return list(self._all)

    def __iter__(self) -> "TokenStream":
        return self

    def __next__(self) -> int:
        with self._cond:
            while True:
                if self._buf:
                    tok = self._buf.popleft()
                    self._cond.notify_all()  # engine may unpark
                    return tok
                if self._done:
                    # buffered tokens always drain first; a typed
                    # failure surfaces MID-stream, after everything
                    # that was generated before it
                    if self._exc is not None and \
                            self.finish_reason != "cancelled":
                        raise self._exc
                    raise StopIteration
                self._cond.wait()

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the stream finishes; the full token list.
        Raises the stream's typed error (incl. :class:`StreamCancelled`
        after a cancel) — partial tokens stay readable via
        :attr:`tokens`. This IS a consumer: it drains the bounded
        buffer while waiting (``_all`` keeps everything), so a parked
        slot resumes — don't mix it with iteration."""
        with self._cond:
            deadline = (time.monotonic() + timeout
                        if timeout is not None else None)
            while not self._done:
                if self._buf:
                    self._buf.clear()  # consumed; engine may unpark
                    self._cond.notify_all()
                rem = None if deadline is None \
                    else deadline - time.monotonic()
                if rem is not None and rem <= 0:
                    raise DeadlineExceeded(
                        f"TokenStream not finished within {timeout}s — "
                        "the request is still decoding (reader "
                        "deadline only; the stream stays accounted)")
                self._cond.wait(rem)
            if self._exc is not None:
                raise self._exc
            return list(self._all)


class _GenRequest:
    __slots__ = ("prompt", "max_new", "temperature", "top_k", "seed",
                 "stream", "deadline", "t_enq", "truncated_by_budget",
                 "slot", "n_generated", "t_first", "spec",
                 "priority", "resumed", "emitted", "preempted")

    def __init__(self, prompt: np.ndarray, max_new: int,
                 temperature: float, top_k: int, seed: int,
                 deadline_s: Optional[float], stream: TokenStream,
                 truncated_by_budget: bool, priority: int = 0,
                 resumed: int = 0):
        # `prompt` includes any previously-emitted tokens a failover
        # replays (`resumed` = how many of its tail are replayed output,
        # NOT client prompt); `emitted` tracks tokens THIS server
        # produced, so preempt/park re-admission can extend the replay.
        self.prompt = prompt
        self.max_new = int(max_new)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.seed = int(seed)
        self.stream = stream
        self.t_enq = time.monotonic()
        self.deadline = (self.t_enq + deadline_s
                         if deadline_s else None)
        self.truncated_by_budget = truncated_by_budget
        self.slot = -1
        self.priority = int(priority)
        self.resumed = int(resumed)
        self.n_generated = int(resumed)
        self.emitted: List[int] = []
        self.preempted = 0
        self.t_first = 0.0
        self.spec = None  # per-request speculator (engine.spec_tokens>0)


# ---------------------------------------------------------------------------
# engine


class GenerationEngine:
    """Device state + compiled executables of the decode loop.

    Owns the per-layer ``[slots, max_seq, heads, dim]`` KV cache and
    the per-slot cursor/token/RNG/sampling arrays, all donated through
    every dispatch. :meth:`prefill` runs one prompt (padded to its
    length bucket) into a slot and samples the first token;
    :meth:`decode` advances EVERY active slot by one token in one
    dispatch. Slot scheduling (who is active, stream delivery,
    deadlines) lives in :class:`GenerationServer` — the engine is
    purely the device side, so it is reusable under a different front
    end.
    """

    def __init__(self, model, slots: Optional[int] = None,
                 max_seq: Optional[int] = None, prefill_buckets=None,
                 eos_id: Optional[int] = None,
                 metrics: Optional[ServingMetrics] = None,
                 cache_dtype: str = "float32",
                 paged: Optional[bool] = None,
                 page_size: Optional[int] = None,
                 pages: Optional[int] = None,
                 prefix_cache: Optional[int] = None,
                 spec_tokens: Optional[int] = None,
                 int8: Optional[bool] = None):
        core_flags.maybe_enable_compilation_cache()
        import jax
        self.metrics = metrics
        self.slots = int(slots if slots is not None
                         else core_flags.flag("serve_gen_slots"))
        self.max_seq = int(max_seq if max_seq is not None
                           else core_flags.flag("serve_gen_max_seq"))
        if self.slots < 1 or self.max_seq < 2:
            raise InvalidArgumentError(
                f"need slots >= 1 and max_seq >= 2, got "
                f"{self.slots}/{self.max_seq}")
        # decode economics (ISSUE 16): paging / speculation / int8 all
        # resolve at construction and ride ONE decode signature
        self.paged = bool(core_flags.flag("serve_gen_paged")
                          if paged is None else paged)
        self.spec_tokens = int(core_flags.flag("serve_gen_spec_tokens")
                               if spec_tokens is None else spec_tokens)
        if self.spec_tokens < 0:
            raise InvalidArgumentError(
                f"spec_tokens must be >= 0, got {self.spec_tokens}")
        # decode window: the fed token + k drafts verified per dispatch.
        # Every window write spans `window` rows, so sequences must stop
        # `decode_margin` short of max_seq — enforced at admission.
        self.window = 1 + self.spec_tokens
        self.decode_margin = self.window - 1
        if self.max_seq <= self.decode_margin + 1:
            raise InvalidArgumentError(
                f"max_seq={self.max_seq} leaves no room under a "
                f"speculative window of {self.window} (margin "
                f"{self.decode_margin}) — shrink serve_gen_spec_tokens")
        self.int8 = bool(core_flags.flag("serve_gen_int8")
                         if int8 is None else int8)
        self.prefill_buckets = self._resolve_prefill_buckets(
            prefill_buckets, self.max_seq)
        self.eos_id = None if eos_id is None else int(eos_id)
        needed_cache = "gen_paged_cache" if self.paged \
            else "gen_slot_cache"
        if not hasattr(model, needed_cache):
            raise InvalidArgumentError(
                "GenerationEngine needs a model with the generation "
                f"contract: {needed_cache}(...) and "
                "forward(ids, cache=, positions=, attn_mask=) -> "
                f"(logits, new_cache); got {type(model).__name__}")
        model_cap = getattr(model, "max_seq", None)
        if model_cap is not None and int(model_cap) < self.max_seq:
            # positions past the model's embedding table would CLAMP
            # under jit (jnp.take semantics) and silently degrade every
            # long sequence — reject the config typed instead
            raise InvalidArgumentError(
                f"engine max_seq={self.max_seq} exceeds the model's "
                f"positional capacity (model.max_seq={int(model_cap)})"
                " — positions past the table would silently clamp; "
                "build the model with max_seq >= the engine's")
        model.eval()
        self._model = model
        self._params = model.functional_state()
        if self.int8:
            # per-channel int8 weight storage; dequant happens INSIDE
            # the trace (_apply_model), so jit args / HBM stay int8
            from ..quantization import quantize_weights_int8
            self._params = quantize_weights_int8(self._params)
        self._lock = locks.make_lock("GenerationEngine._lock")
        # trace-side-effect counters — the "exactly one decode compile"
        # acceptance gate reads decode_compile_count
        self.decode_compile_count = 0
        self.decode_dispatch_count = 0
        self.prefill_compile_counts: Dict[int, int] = {}
        self.prefill_dispatch_counts: Dict[int, int] = {}

        # device state (donated through every dispatch)
        import jax.numpy as jnp
        if self.paged:
            self.page_size = int(
                page_size if page_size is not None
                else core_flags.flag("serve_gen_kv_page_size"))
            if self.page_size < 1:
                raise InvalidArgumentError(
                    f"page_size must be >= 1, got {self.page_size}")
            self.pages_per_slot = -(-self.max_seq // self.page_size)
            n_pages = int(pages if pages is not None
                          else core_flags.flag("serve_gen_kv_pages"))
            if n_pages <= 0:
                # auto: worst case every slot dense, + the parking page
                n_pages = self.slots * self.pages_per_slot + 1
            prefix_entries = int(
                prefix_cache if prefix_cache is not None
                else core_flags.flag("serve_gen_prefix_cache"))
            self.pool = PagePool(n_pages, self.page_size,
                                 prefix_entries)
            cache = model.gen_paged_cache(n_pages, self.page_size,
                                          cache_dtype)
            self._kv = [(c.k.data, c.v.data) for c in cache]
            # host-authoritative page table, mirrored to device on
            # change; rows are parking-filled beyond a slot's chain
            self._table_np = np.full(
                [self.slots, self.pages_per_slot], PARKING_PAGE,
                np.int32)
            self._table = jnp.asarray(self._table_np)
            self._slot_pages: List[List[int]] = [
                [] for _ in range(self.slots)]
            # K+V bytes of ONE page across every layer (sizing + the
            # gen_kv_page_bytes gauge)
            self._page_bytes = sum(
                int(np.prod(k.shape[1:])) * k.dtype.itemsize
                + int(np.prod(v.shape[1:])) * v.dtype.itemsize
                for k, v in self._kv)
        else:
            self.pool = None
            self.page_size = 0
            self.pages_per_slot = 0
            self._page_bytes = 0
            cache = model.gen_slot_cache(self.slots, self.max_seq,
                                         cache_dtype)
            self._kv = [(c.k.data, c.v.data) for c in cache]
            self._table_np = np.zeros([1, 1], np.int32)
            self._table = jnp.asarray(self._table_np)
            self._slot_pages = [[] for _ in range(self.slots)]
        # host mirror of _lengths: page-capacity math and window
        # delivery never pay a device readback for it
        self._host_len = np.zeros([self.slots], np.int64)
        self._warming = False
        self.last_page_faults: Dict[int, KVPoolExhausted] = {}
        self._last_pool_stats: Dict[str, int] = {}
        self._evictions_published = 0
        self._lengths = jnp.zeros([self.slots], jnp.int32)
        self._tokens = jnp.zeros([self.slots], jnp.int32)
        self._keys = jnp.zeros(
            [self.slots] + list(jax.random.key_data(
                jax.random.key(0)).shape), jnp.uint32)
        self._temps = jnp.zeros([self.slots], jnp.float32)
        self._topks = jnp.zeros([self.slots], jnp.int32)

        self._decode_jit = jax.jit(self._decode_fn,
                                   donate_argnums=(1,))
        self._prefill_jits: Dict[int, object] = {}
        # None when debug_jit_sanitizer is off: decode's compile-once
        # contract becomes enforceable (limit=1) and the donated KV
        # cache is poisoned after every dispatch
        self._jsan = jit_sanitizer.site("GenerationEngine")
        # executable cost attribution (obs.costmodel, ISSUE 13):
        # computed lazily per executable on the first instrumented
        # dispatch (obs_metrics on); the HBM census tags the engine's
        # device state per subsystem (weakref — dies with the engine)
        self._decode_cost = None
        self._prefill_costs: Dict[int, object] = {}
        from ..obs import hbm as obs_hbm
        obs_hbm.register("params", self, lambda e: e._params,
                         name="GenerationEngine.params")
        # the page pools/table ride the kv_cache subsystem: census
        # coverage stays 1.0 under paging (ISSUE 16 satellite), and the
        # small per-slot state arrays are accounted rather than leaked
        # into "other"
        obs_hbm.register("kv_cache", self,
                         lambda e: (e._kv, e._table, e._lengths,
                                    e._tokens, e._keys, e._temps,
                                    e._topks),
                         name="GenerationEngine.kv")

    @staticmethod
    def _resolve_prefill_buckets(buckets, max_seq):
        # the batch-bucket policy, retargeted at the prompt-length axis
        # (spec_flag keeps it off the serve_buckets BATCH flag)
        out = resolve_buckets(buckets, max_seq,
                              spec_flag="serve_gen_prefill_buckets")
        if out[-1] > max_seq:
            raise InvalidArgumentError(
                f"prefill bucket {out[-1]} exceeds serve_gen_max_seq="
                f"{max_seq} — a prompt that long could never decode")
        return out

    # -- traced bodies ------------------------------------------------------

    def _apply_model(self, params, ids, caches, positions, attn_mask):
        """Run the model functionally on raw arrays (the
        InferenceEngine idiom: params ride as jit args, dropout off,
        RNG pinned)."""
        import jax
        from ..autograd import engine as autograd_engine
        from ..core.generator import rng_scope
        from ..core.tensor import Tensor
        if self.int8:
            # int8 weights dequantize per-channel inside the trace; XLA
            # fuses the cast+scale into the consuming matmul, so HBM
            # traffic (and the jit args) stay int8
            from ..quantization import dequantize_weights
            params = dequantize_weights(params)
        mask_t = None if attn_mask is None \
            else Tensor(attn_mask, stop_gradient=True)
        with autograd_engine.no_grad(), rng_scope(jax.random.key(0)):
            with self._model.load_functional_state(params):
                logits, new_caches = self._model(
                    Tensor(ids, stop_gradient=True),
                    cache=caches,
                    positions=Tensor(positions, stop_gradient=True),
                    attn_mask=mask_t)
        return logits.data, new_caches

    def _decode_fn(self, params, kv, table, lengths, tokens, keys,
                   temps, topks, active, drafts, ndrafts):
        """Counted wrapper over :meth:`_decode_body` — the increment
        runs only while TRACING (the standard trace-side-effect
        counter). The cost model lowers ``_decode_body`` directly so
        attribution can never corrupt the compile-ONCE accounting."""
        with self._lock:
            self.decode_compile_count += 1
        if self.metrics is not None:
            self.metrics.counter("gen_decode_compiles_total").inc()
        return self._decode_body(params, kv, table, lengths, tokens,
                                 keys, temps, topks, active, drafts,
                                 ndrafts)

    def _decode_body(self, params, kv, table, lengths, tokens, keys,
                     temps, topks, active, drafts, ndrafts):
        """One decode WINDOW for every slot; compiled exactly once.

        The window is ``[fed token, draft_1..draft_k]`` (k =
        ``spec_tokens``; k=0 reduces exactly to the classic one-token
        step). All W rows run through the model in one dispatch;
        row i's logits give the target-distribution sample for position
        pos+i+1, and the draft chain is verified by *equality against
        the engine's own deterministic key schedule*: row i's sample is
        produced iff every earlier draft matched its sample. The RNG
        key advances once per PRODUCED token — so the (seed, token
        index) → draw mapping, and therefore the output stream, is
        bit-identical to non-speculative decode whatever the drafts
        were. Rejected-draft KV rows are stale garbage past the new
        cursor; the next window overwrites them before any mask ever
        exposes them.

        ``active`` gates advancement — inactive slots keep their
        token/length/key, so parking a slot costs nothing and never
        retraces. Paged mode reads/writes through ``table`` (dense mode
        carries a [1,1] placeholder); page faults and draft contents
        are DATA, never shapes, preserving the one-compile contract.
        """
        import jax
        import jax.numpy as jnp
        from ..nn import MultiHeadAttention
        from ..nn.decode import sample_logits_array
        from ..core.tensor import Tensor
        S, M, W = self.slots, self.max_seq, self.window
        pos = jnp.minimum(lengths, M - W)
        ids = jnp.concatenate([tokens[:, None], drafts], axis=1)
        positions = pos[:, None] + jnp.arange(W, dtype=jnp.int32)[None]
        if self.paged:
            caches = [MultiHeadAttention.PagedCache(
                Tensor(k, stop_gradient=True),
                Tensor(v, stop_gradient=True),
                Tensor(table, stop_gradient=True),
                Tensor(pos, stop_gradient=True)) for k, v in kv]
            logits, new_caches = self._apply_model(
                params, ids, caches, positions, None)
        else:
            caches = [MultiHeadAttention.GenCache(
                Tensor(k, stop_gradient=True),
                Tensor(v, stop_gradient=True),
                Tensor(pos, stop_gradient=True)) for k, v in kv]
            # window row i attends keys j <= pos + i (row 0 == the
            # classic "fed token just written AT pos" mask)
            qpos = positions
            mask = (jnp.arange(M)[None, None, None, :]
                    <= qpos[:, None, :, None])
            logits, new_caches = self._apply_model(
                params, ids, caches, positions, mask)
        lg = logits.astype(jnp.float32)              # [S, W, V]
        # dpad[i] = the draft proposed for row i+1 (last column unused)
        dpad = jnp.concatenate(
            [drafts, jnp.zeros((S, 1), drafts.dtype)], axis=1)

        def chain(lg_row, dpad_row, kd0, temp, topk, act, nd):
            def step(carry, x):
                kd, ok = carry
                i, lrow, dnext = x
                kb = jax.random.wrap_key_data(kd)
                s = sample_logits_array(
                    lrow, jax.random.fold_in(kb, 0), temp,
                    topk).astype(jnp.int32)
                kd2 = jnp.where(ok, jax.random.key_data(
                    jax.random.fold_in(kb, 1)), kd)
                ok2 = ok & (i < nd) & (dnext == s)
                return (kd2, ok2), (s, ok)
            (kdf, _), (toks, flags) = jax.lax.scan(
                step, (kd0, act),
                (jnp.arange(W), lg_row, dpad_row))
            return toks, flags, kdf

        toks, flags, new_keys = jax.vmap(chain)(
            lg, dpad, keys, temps, topks, active, ndrafts)
        n_prod = jnp.sum(flags.astype(jnp.int32), axis=1)
        last = jnp.take_along_axis(
            toks, jnp.maximum(n_prod - 1, 0)[:, None], axis=1)[:, 0]
        nxt = jnp.where(n_prod > 0, last, tokens)
        new_lengths = jnp.minimum(lengths + n_prod,
                                  M - self.decode_margin)
        new_kv = [(c.k.data, c.v.data) for c in new_caches]
        return new_kv, new_lengths, nxt, new_keys, toks, flags

    def _prefill_fn_for(self, bucket: int):
        """Build (once per bucket) the counted prefill wrapper over
        :meth:`_prefill_body` (same counted/uncounted split as
        decode)."""
        import jax

        def prefill_fn(params, kv, ids, length, slot, key, temp, topk,
                       row_pages):
            with self._lock:
                self.prefill_compile_counts[bucket] = \
                    self.prefill_compile_counts.get(bucket, 0) + 1
            if self.metrics is not None:
                self.metrics.counter("gen_prefill_compiles_total").inc()
            return self._prefill_body(bucket, params, kv, ids, length,
                                      slot, key, temp, topk, row_pages)
        return jax.jit(prefill_fn, donate_argnums=(1,))

    def _prefill_body(self, bucket, params, kv, ids, length, slot, key,
                      temp, topk, row_pages):
        """The prefill computation: the whole padded prompt in one
        causal pass, K/V written into the slot's cache rows — dense:
        one dynamic_update_slice per layer at the slot row; paged: a
        per-row scatter steered by ``row_pages`` ([bucket] int32, the
        target page per prompt position). Shared prefix pages and
        beyond-prompt padding rows target the parking page, so a reused
        page is NEVER rewritten (bit-stable for every cohabitant) and
        padding garbage never lands in real pages. First token sampled
        from the last REAL position."""
        import jax
        import jax.numpy as jnp
        from ..nn import MultiHeadAttention
        from ..nn.decode import sample_logits_array
        from ..core.tensor import Tensor
        L = bucket
        small = []
        for k_arr, v_arr in kv:
            H, D = k_arr.shape[2], k_arr.shape[3]
            z = jnp.zeros((1, L, H, D), k_arr.dtype)
            small.append(MultiHeadAttention.GenCache(
                Tensor(z, stop_gradient=True),
                Tensor(z, stop_gradient=True),
                Tensor(jnp.zeros((1,), jnp.int32),
                       stop_gradient=True)))
        positions = jnp.arange(L, dtype=jnp.int32)[None]
        causal = jnp.tril(jnp.ones((L, L), bool))[None, None]
        logits, filled = self._apply_model(
            params, ids[None], small, positions, causal)
        new_kv = []
        if self.paged:
            off = jnp.arange(L) % self.page_size
            for (k_arr, v_arr), c in zip(kv, filled):
                new_kv.append((
                    k_arr.at[row_pages, off].set(
                        c.k.data[0].astype(k_arr.dtype)),
                    v_arr.at[row_pages, off].set(
                        c.v.data[0].astype(v_arr.dtype))))
        else:
            for (k_arr, v_arr), c in zip(kv, filled):
                new_kv.append((
                    jax.lax.dynamic_update_slice(
                        k_arr, c.k.data.astype(k_arr.dtype),
                        (slot, 0, 0, 0)),
                    jax.lax.dynamic_update_slice(
                        v_arr, c.v.data.astype(v_arr.dtype),
                        (slot, 0, 0, 0))))
        last = jnp.take(logits[0], length - 1,
                        axis=0).astype(jnp.float32)
        kb = jax.random.wrap_key_data(key)
        first = sample_logits_array(
            last, jax.random.fold_in(kb, 0), temp, topk)
        carry = jax.random.key_data(jax.random.fold_in(kb, 1))
        return new_kv, first.astype(jnp.int32), carry

    # -- host-side dispatch -------------------------------------------------

    @staticmethod
    def resume_key(seed: int, start_index: int = 0) -> "object":
        """The raw key data that draws token ``start_index + 1`` of the
        request seeded ``seed`` — the replay foundation of mid-stream
        failover. The engine's schedule depends only on (seed, token
        index): prefill starts from ``fold_in(key(seed), 0)`` and every
        PRODUCED token advances the carry once via ``fold_in(k, 1)``,
        so host-advancing the chain ``start_index`` steps and
        prefilling over ``prompt + tokens already emitted`` continues
        the stream bit-identically on any replica (greedy ignores keys
        entirely; sampled draws re-join the exact chain)."""
        import jax
        k = jax.random.fold_in(
            jax.random.key(int(seed) & 0x7FFFFFFF), 0)
        for _ in range(int(start_index)):
            k = jax.random.fold_in(k, 1)
        return jax.random.key_data(k)

    def check_kv_invariants(self, extra_holders=()) -> None:
        """Debug sweep (``FLAGS_debug_kv_refcount``): verify the page
        pool's refcounts against the engine's live slot chains (+ any
        ``extra_holders`` page lists, e.g. chaos-held pages). Raises
        typed :class:`~paddle1_tpu.serving.errors.KVPageAccountingError`
        at the tick that corrupted accounting. No-op when unpaged."""
        if not self.paged:
            return
        holders = [c for c in self._slot_pages if c]
        holders.extend(list(x) for x in extra_holders if x)
        self.pool.check_invariants(holders)

    def bucket_for(self, prompt_len: int) -> int:
        if prompt_len < 1:
            raise InvalidArgumentError(
                f"need a prompt of >= 1 token, got {prompt_len}")
        for b in self.prefill_buckets:
            if prompt_len <= b:
                return b
        raise InvalidArgumentError(
            f"prompt of {prompt_len} tokens exceeds the largest "
            f"prefill bucket {self.prefill_buckets[-1]} (buckets "
            f"{list(self.prefill_buckets)}) — raise "
            "serve_gen_prefill_buckets/serve_gen_max_seq")

    def _release_slot_pages(self, slot: int) -> None:
        """Drop the slot's page refs and park its table row (paged)."""
        if not self.paged:
            return
        pages = self._slot_pages[slot]
        if pages:
            self.pool.release(pages)
            self._slot_pages[slot] = []
        if (self._table_np[slot] != PARKING_PAGE).any():
            import jax.numpy as jnp
            self._table_np[slot, :] = PARKING_PAGE
            self._table = jnp.asarray(self._table_np)

    def _alloc_prefill_pages(self, slot: int,
                             prompt: np.ndarray) -> np.ndarray:
        """Claim the slot's prefill page chain (prefix-shared head +
        private tail) and return the per-row scatter targets. Shared
        pages' rows target parking — only the FIRST request ever writes
        a shared page, so cohabitants' bits can never be perturbed —
        and the whole chain is refcounted against the slot. Raises
        KVPoolExhausted (after releasing anything claimed) when the
        pool cannot serve; the caller never holds a half-claimed
        chain."""
        P = int(np.shape(prompt)[0])
        ps = self.page_size
        prompt_i32 = np.asarray(prompt, np.int32).reshape(-1)
        self._release_slot_pages(slot)  # warm-up / crash-reuse safety
        shared: List[int] = []
        if not self._warming:
            shared = self.pool.lookup_prefix(prompt_i32)
        n_needed = (P - 1) // ps + 1
        n_shared = min(len(shared), n_needed)
        if n_shared < len(shared):  # over-long hit (can't happen: the
            self.pool.release(shared[n_shared:])  # registry only holds
            shared = shared[:n_shared]            # full-page chains)
        try:
            private = self.pool.alloc(n_needed - n_shared)
        except KVPoolExhausted:
            self.pool.release(shared)
            raise
        chain = shared + private
        if not self._warming:
            self.pool.register_prefix(prompt_i32, chain)
        import jax.numpy as jnp
        self._slot_pages[slot] = chain
        self._table_np[slot, :] = PARKING_PAGE
        self._table_np[slot, :len(chain)] = chain
        self._table = jnp.asarray(self._table_np)
        if self.metrics is not None:
            from ..obs.registry import metrics_on
            if metrics_on():
                self.metrics.counter(
                    "gen_kv_prefix_hits_total").inc(n_shared)
        # per-row targets: shared head + padding rows → parking
        row_pages = np.full([self.bucket_for(P)], PARKING_PAGE,
                            np.int32)
        for i in range(n_shared * ps, P):
            row_pages[i] = chain[i // ps]
        return row_pages

    def prefill(self, slot: int, prompt: np.ndarray, temperature: float,
                top_k: int, seed: int, start_index: int = 0) -> int:
        """Run one prompt into ``slot``; returns the first generated
        token (host int). One dispatch on the bucket executable.

        ``start_index > 0`` is the failover/preemption replay path:
        ``prompt`` then carries the client prompt PLUS the first
        ``start_index`` tokens already emitted elsewhere, and the RNG
        key resumes at :meth:`resume_key` — the returned "first" token
        is token ``start_index + 1`` of the original stream, bit-
        identical to an uninterrupted run (the prefill logits at the
        last real position equal the decode step's, and the draw key is
        the same chain entry)."""
        import jax
        import jax.numpy as jnp
        P = int(np.shape(prompt)[0])
        bucket = self.bucket_for(P)
        if P + 1 > self.max_seq - self.decode_margin:
            raise InvalidArgumentError(
                f"prompt of {P} tokens leaves no room to generate "
                f"within serve_gen_max_seq={self.max_seq} (speculative "
                f"window margin {self.decode_margin})")
        if self.paged:
            row_pages = self._alloc_prefill_pages(slot, prompt)
        else:
            row_pages = np.zeros([bucket], np.int32)
        fn = self._prefill_jits.get(bucket)
        if fn is None:
            fn = self._prefill_jits.setdefault(
                bucket, self._prefill_fn_for(bucket))
        ids = np.zeros([bucket], np.int32)
        ids[:P] = np.asarray(prompt, np.int32)
        base = self.resume_key(seed, start_index)
        with self._lock:
            self.prefill_dispatch_counts[bucket] = \
                self.prefill_dispatch_counts.get(bucket, 0) + 1
        donated = None
        if self._jsan is not None:
            donated = [a for pair in self._kv for a in pair]
            self._jsan.guard_args(donated, "prefill")
        self._kv, first, carry = fn(
            self._params, self._kv, jnp.asarray(ids),
            np.int32(P), np.int32(slot), base,
            np.float32(temperature), np.int32(top_k),
            jnp.asarray(row_pages))
        if donated is not None:
            self._jsan.poison_donated(donated)
        if self.metrics is not None \
                and bucket not in self._prefill_costs:
            self._maybe_publish_prefill_cost(bucket)
        first = int(np.asarray(first))
        # slot bookkeeping (small host-side .at updates, off the jitted
        # path so they can't force a retrace)
        self._lengths = self._lengths.at[slot].set(np.int32(P))
        self._host_len[slot] = P
        self._tokens = self._tokens.at[slot].set(np.int32(first))
        self._keys = self._keys.at[slot].set(carry)
        self._temps = self._temps.at[slot].set(np.float32(temperature))
        self._topks = self._topks.at[slot].set(np.int32(top_k))
        return first

    def ensure_page_capacity(self, active_mask: np.ndarray
                             ) -> Dict[int, BaseException]:
        """Page-fault handler, run on the host BEFORE each decode
        dispatch (paged mode): any active slot whose next ``window``
        writes would spill past its mapped chain gets fresh pages
        appended to its table row. Faults change only the table *data*
        — shapes are pinned at ``[slots, max_pages_per_slot]`` — so the
        decode executable is untouched (compile-once survives growth).
        Returns ``{slot: KVPoolExhausted}`` for slots the pool could
        not extend; the caller masks those out and finishes them."""
        if not self.paged:
            return {}
        import jax.numpy as jnp
        failed: Dict[int, BaseException] = {}
        faulted = 0
        dirty = False
        for s in range(self.slots):
            if not bool(active_mask[s]):
                continue
            need = min(
                (int(self._host_len[s]) + self.window - 1)
                // self.page_size + 1,
                self.pages_per_slot)
            have = len(self._slot_pages[s])
            if need <= have:
                continue
            try:
                fresh = self.pool.alloc(need - have)
            except KVPoolExhausted as e:
                failed[s] = e
                continue
            self._table_np[s, have:have + len(fresh)] = fresh
            self._slot_pages[s].extend(fresh)
            faulted += len(fresh)
            dirty = True
        if dirty:
            self._table = jnp.asarray(self._table_np)
        if faulted and self.metrics is not None:
            from ..obs.registry import metrics_on
            if metrics_on():
                self.metrics.counter(
                    "gen_kv_page_faults_total").inc(faulted)
        return failed

    def decode(self, active_mask: np.ndarray,
               drafts: Optional[np.ndarray] = None,
               ndrafts: Optional[np.ndarray] = None):  # hot-path: one dispatch per step
        """One decode step for the whole slot batch; returns
        ``(tokens, accepted)`` — both ``[slots, window]`` host arrays.
        ``tokens[s, i]`` is the i-th token the sample chain produced;
        ``accepted[s, i]`` marks the chain entries that are real output
        (always column 0 for live slots; further columns only when
        speculation accepted draft tokens). Exactly one device
        dispatch regardless of drafts, faults, or arrival pattern."""
        import jax.numpy as jnp
        self.last_page_faults = self.ensure_page_capacity(active_mask)
        if self.last_page_faults:
            active_mask = np.asarray(active_mask, bool).copy()
            for s in self.last_page_faults:
                active_mask[s] = False
        if drafts is None:
            drafts = np.zeros([self.slots, self.spec_tokens], np.int32)
        if ndrafts is None:
            ndrafts = np.zeros([self.slots], np.int32)
        with self._lock:
            self.decode_dispatch_count += 1
        donated = None
        if self._jsan is not None:
            donated = [a for pair in self._kv for a in pair]
            self._jsan.guard_args(donated, "decode")
        (self._kv, self._lengths, self._tokens, self._keys, toks,
         flags) = self._decode_jit(
            self._params, self._kv, self._table, self._lengths,
            self._tokens, self._keys, self._temps, self._topks,
            jnp.asarray(active_mask, bool),
            jnp.asarray(drafts, jnp.int32).reshape(
                self.slots, self.spec_tokens) if self.spec_tokens
            else jnp.zeros([self.slots, 0], jnp.int32),
            jnp.asarray(ndrafts, jnp.int32).reshape(self.slots))
        if donated is not None:
            self._jsan.poison_donated(donated)
            # the compile-once contract, enforceable: a second decode
            # compile means a signature leaked into the pinned shape
            self._jsan.note_signatures(self.decode_compile_count,
                                       kind="decode recompile", limit=1)
        if self.metrics is not None and self._decode_cost is None:
            self._maybe_publish_decode_cost()
        jit_sanitizer.note_host_sync("gen_token_readback")
        toks_np = np.asarray(toks)  # noqa: hidden-host-sync — the ONE intended readback
        flags_np = np.asarray(flags, bool)
        self._host_len += flags_np.sum(axis=1).astype(np.int64)
        np.minimum(self._host_len,
                   self.max_seq - self.decode_margin,
                   out=self._host_len)
        return toks_np, flags_np

    # -- executable cost attribution (ISSUE 13) -----------------------------

    def decode_cost(self):
        """FLOPs + bytes of ONE decode dispatch (the whole slot batch,
        one token each) — XLA cost analysis of an UNCOUNTED lowering
        of :meth:`_decode_body` (lowering the counted jit would break
        the compile-ONCE accounting). Memoized: the decode signature
        is pinned, so one analysis covers the engine's lifetime."""
        if self._decode_cost is None:
            import jax
            import jax.numpy as jnp
            from ..obs import costmodel as obs_costmodel
            args = (self._params, self._kv, self._table, self._lengths,
                    self._tokens, self._keys, self._temps, self._topks,
                    jnp.zeros([self.slots], bool),
                    jnp.zeros([self.slots, self.spec_tokens],
                              jnp.int32),
                    jnp.zeros([self.slots], jnp.int32))
            fb = obs_costmodel.tree_size_cost(
                self._params, batch=self._tokens, extra=self._kv)
            self._decode_cost = obs_costmodel.analyze(
                lambda: jax.jit(self._decode_body).lower(*args),
                fallback=fb)
        return self._decode_cost

    def _maybe_publish_decode_cost(self) -> None:
        from ..obs.registry import metrics_on
        if not metrics_on():
            return
        cost = self.decode_cost()
        self.metrics.gauge("gen_decode_flops").set(cost.flops)
        self.metrics.gauge("gen_decode_bytes").set(cost.bytes_accessed)
        self.metrics.gauge("gen_cost_exact").set(
            1.0 if cost.exact else 0.0)

    def prefill_cost(self, bucket: int):
        """FLOPs + bytes of one prefill dispatch at ``bucket`` —
        same uncounted-lowering discipline as :meth:`decode_cost`."""
        c = self._prefill_costs.get(bucket)
        if c is None:
            import jax
            import jax.numpy as jnp
            import numpy as _np
            from ..obs import costmodel as obs_costmodel
            ids = jnp.zeros([bucket], jnp.int32)
            base = jax.random.key_data(jax.random.fold_in(
                jax.random.key(0), 0))
            fb = obs_costmodel.tree_size_cost(self._params, batch=ids,
                                              extra=self._kv)
            c = obs_costmodel.analyze(
                lambda: jax.jit(
                    lambda *a: self._prefill_body(bucket, *a)).lower(
                    self._params, self._kv, ids, _np.int32(1),
                    _np.int32(0), base, _np.float32(0.0),
                    _np.int32(0), jnp.zeros([bucket], jnp.int32)),
                fallback=fb)
            self._prefill_costs[bucket] = c
        return c

    def _maybe_publish_prefill_cost(self, bucket: int) -> None:
        from ..obs.registry import metrics_on
        if not metrics_on():
            return
        cost = self.prefill_cost(bucket)
        self.metrics.gauge(f"gen_prefill_bucket_{bucket}_flops").set(
            cost.flops)
        self.metrics.gauge(f"gen_prefill_bucket_{bucket}_bytes").set(
            cost.bytes_accessed)

    def publish_kv_metrics(self) -> None:
        """Mirror the page pool's host accounting as gauges/counters
        (paged mode; no-op otherwise). ``gen_kv_page_evictions_total``
        publishes the pool's cumulative count via ``inc(delta)`` so the
        counter stays monotone across calls."""
        if not self.paged or self.metrics is None:
            return
        st = self.pool.stats()
        self._last_pool_stats = st
        self.metrics.gauge("gen_kv_pages_in_use").set(
            st["pages_in_use"])
        self.metrics.gauge("gen_kv_pages_free").set(st["pages_free"])
        self.metrics.gauge("gen_kv_pages_cached").set(
            st["pages_cached"])
        self.metrics.gauge("gen_kv_page_bytes").set(self._page_bytes)
        ev = self.metrics.counter("gen_kv_page_evictions_total")
        ev.inc(st["evictions"] - self._evictions_published)
        self._evictions_published = st["evictions"]

    def release(self, slot: int) -> None:
        """Free a slot: reset its cursor so idle writes stay parked at
        row 0 (the next prefill overwrites everything it will read) and
        — in paged mode — return its page refs to the pool in the SAME
        call (the cancel/deadline contract: by the time the scheduler
        tick that retired the request ends, its pages are reusable)."""
        self._lengths = self._lengths.at[slot].set(np.int32(0))
        self._host_len[slot] = 0
        self._release_slot_pages(slot)

    def warm_up(self) -> int:
        """Pre-compile every prefill bucket plus the decode executable
        (first-token latency stops including XLA compiles). Returns the
        number of executables compiled. Slot state is reset after.
        Warm-up prompts bypass the prefix registry (``_warming``): the
        zero-token probe prompts must not squat pages or pollute the
        prefix cache."""
        import jax
        import jax.numpy as jnp
        self._warming = True
        try:
            n = 0
            for b in self.prefill_buckets:
                self.prefill(0, np.zeros(
                    [min(b, self.max_seq - self.window)],
                    np.int32), 0.0, 0, 0)
                n += 1
            self.decode(np.zeros([self.slots], bool))
            n += 1
            jax.block_until_ready(self._kv[0][0])
        finally:
            self._warming = False
        self.release(0)
        self._lengths = jnp.zeros([self.slots], jnp.int32)
        self._tokens = jnp.zeros([self.slots], jnp.int32)
        self._host_len[:] = 0
        return n


# ---------------------------------------------------------------------------
# server


class GenerationServer:
    """Streaming front end over a :class:`GenerationEngine`: admission
    control, per-request deadlines/token budgets, graceful drain — the
    PR 4 Server contracts with token-level accounting. One loop thread
    owns all slot scheduling (iteration-level continuous batching: it
    admits new prompts into free slots between decode steps)."""

    def __init__(self, model, slots: Optional[int] = None,
                 max_seq: Optional[int] = None, prefill_buckets=None,
                 eos_id: Optional[int] = None,
                 token_budget: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 queue_depth: Optional[int] = None,
                 stream_buffer: Optional[int] = None,
                 warmup: bool = False,
                 metrics: Optional[ServingMetrics] = None,
                 preempt: Optional[bool] = None):
        self.metrics = metrics if metrics is not None else ServingMetrics()
        if isinstance(model, GenerationEngine):
            if (slots is not None or max_seq is not None
                    or prefill_buckets is not None):
                raise InvalidArgumentError(
                    "slots/max_seq/prefill_buckets cannot be applied "
                    "to a pre-built GenerationEngine — pass them to "
                    "GenerationEngine(), or hand the raw model over")
            self.engine = model
            self.engine.metrics = self.metrics  # latest-wins rebind
            if eos_id is not None:
                self.engine.eos_id = int(eos_id)
        else:
            self.engine = GenerationEngine(
                model, slots=slots, max_seq=max_seq,
                prefill_buckets=prefill_buckets, eos_id=eos_id,
                metrics=self.metrics)
        self.token_budget = int(
            token_budget if token_budget is not None
            else core_flags.flag("serve_gen_token_budget"))
        dl = deadline_ms if deadline_ms is not None \
            else core_flags.flag("serve_deadline_ms")
        self.default_deadline_ms = float(dl) if dl else None
        self.queue_depth = int(
            queue_depth if queue_depth is not None
            else core_flags.flag("serve_queue_depth"))
        self.stream_buffer = int(
            stream_buffer if stream_buffer is not None
            else core_flags.flag("serve_gen_stream_buffer"))
        # KV-pressure graceful degradation (preempt/park/re-admit
        # instead of KVPoolExhausted) — only meaningful under paging
        self.preempt = bool(core_flags.flag("serve_gen_preempt")
                            if preempt is None else preempt) \
            and self.engine.paged
        self._warmup = bool(warmup)
        self._q: "queue.Queue[_GenRequest]" = queue.Queue(self.queue_depth)
        self._drain_event = threading.Event()
        self._admit_lock = locks.make_lock("GenerationServer._admit_lock")
        self._accepting = False          # guarded-by: self._admit_lock
        self._loop: Optional[_GenerationLoop] = None
        self._seed_counter = [0]         # guarded-by: self._admit_lock

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "GenerationServer":
        if self._loop is not None and self._loop.is_alive():
            return self
        self._drain_event.clear()
        supervised = core_health.supervised()
        core_health.beat()
        core_health.add_drain_callback(self._drain_event.set)
        if core_health.drain_requested():
            self._drain_event.set()
        if not supervised and threading.current_thread() is \
                threading.main_thread():
            from .server import install_standalone_sigterm_drain
            install_standalone_sigterm_drain()
        if self._warmup:
            n = self.engine.warm_up()
            self.metrics.counter("warmup_executables_total").inc(n)
        self._loop = _GenerationLoop(self.engine, self._q,
                                     self.metrics, self._drain_event,
                                     preempt=self.preempt)
        self._loop.start()
        with self._admit_lock:
            self._accepting = True
        return self

    @property
    def running(self) -> bool:
        return (self._loop is not None and self._loop.is_alive()
                and self._accepting)

    def __enter__(self) -> "GenerationServer":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.drain()
        return False

    # -- request path -------------------------------------------------------

    def submit(self, prompt_ids: Sequence[int],
               max_new_tokens: Optional[int] = None,
               temperature: float = 0.0, top_k: int = 0,
               seed: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               priority: int = 0,
               resume_tokens: Optional[Sequence[int]] = None
               ) -> TokenStream:
        """Enqueue one prompt; returns its :class:`TokenStream`.
        Sheds with :class:`ServerOverloaded` (bounded queue) or raises
        :class:`ServerClosed` (draining/stopped) synchronously.
        ``temperature<=0`` is greedy; ``seed`` pins the sampled draws
        (per-request stream — a request's tokens are identical whether
        it decodes alone or in a full batch).

        ``priority`` (0 = highest) steers KV-pressure preemption under
        ``serve_gen_preempt``: lower-priority streams yield pages
        first. ``resume_tokens`` is the mid-stream failover replay
        path: the tokens a previous replica already emitted for this
        (prompt, seed) stream — they are prefilled (not re-delivered),
        the RNG chain is advanced past them, and the stream continues
        from token ``len(resume_tokens) + 1``, bit-identical to the
        uninterrupted run. ``max_new_tokens`` counts the ORIGINAL
        target (resumed tokens included), so budgets and length caps
        land on the same token they always would."""
        if not self._accepting or self._drain_event.is_set():
            raise ServerClosed(
                "generation server is draining/stopped — not admitting")
        if self._loop is None or not self._loop.is_alive():
            raise ServerClosed(
                "generation server not started (or its loop died: "
                f"{self._loop.fatal!r})" if self._loop is not None
                else "generation server not started — call start()")
        prompt = np.asarray(
            getattr(prompt_ids, "numpy", lambda: prompt_ids)(),
            ).astype(np.int64).reshape(-1)
        if prompt.size < 1:
            raise InvalidArgumentError("submit needs >= 1 prompt token")
        resume = np.asarray(
            [] if resume_tokens is None else resume_tokens,
            np.int64).reshape(-1)
        full = np.concatenate([prompt, resume]) if resume.size \
            else prompt
        self.engine.bucket_for(full.size)  # typed on oversize NOW
        # room is counted from the ORIGINAL prompt: the resumed stream
        # must cap at the same total token the uninterrupted run would
        room = (self.engine.max_seq - int(prompt.size)
                - self.engine.decode_margin)
        if room < 1 or room <= resume.size:
            raise InvalidArgumentError(
                f"prompt of {prompt.size} (+{resume.size} resumed) "
                f"tokens leaves no room to generate within "
                f"max_seq={self.engine.max_seq} (speculative window "
                f"margin {self.engine.decode_margin})")
        asked = int(max_new_tokens) if max_new_tokens is not None \
            else self.token_budget
        if asked < 1:
            raise InvalidArgumentError(
                f"max_new_tokens must be >= 1, got {asked}")
        # the server-side budget/capacity cap: a stream cut short by it
        # fails typed mid-stream (DeadlineExceeded) instead of silently
        # truncating — the client asked for more than it will get
        max_new = min(asked, self.token_budget, room)
        truncated = max_new < asked
        if resume.size >= max_new:
            raise InvalidArgumentError(
                f"resume_tokens already carries {resume.size} of a "
                f"{max_new}-token stream — nothing left to generate "
                "(the stream had finished; don't re-admit it)")
        if resume.size and seed is None:
            raise InvalidArgumentError(
                "resume_tokens needs the original seed — a replayed "
                "continuation is only bit-identical on the same "
                "(seed, token index) chain")
        if seed is None:
            with self._admit_lock:
                self._seed_counter[0] += 1
                seed = self._seed_counter[0]
        dl = deadline_ms if deadline_ms is not None \
            else self.default_deadline_ms
        stream = TokenStream(self.stream_buffer)
        req = _GenRequest(full.astype(np.int32), max_new,
                          float(temperature), int(top_k), int(seed),
                          dl / 1e3 if dl else None, stream, truncated,
                          priority=int(priority),
                          resumed=int(resume.size))
        with self._admit_lock:
            if not self._accepting or self._drain_event.is_set():
                raise ServerClosed(
                    "generation server is draining/stopped — not "
                    "admitting")
            self.metrics.counter("requests_total").inc()
            try:
                self._q.put_nowait(req)
            except queue.Full:
                self.metrics.counter("shed_total").inc()
                raise ServerOverloaded(
                    f"generation queue depth {self.queue_depth} "
                    "exhausted — request shed (scale out, raise "
                    "serve_queue_depth, or slow the client)") from None
        lo = self._loop
        if self._drain_event.is_set() and lo is not None \
                and lo.drained.is_set():
            # lost the admission race against a lockless drain latch
            # (SIGTERM/health callback): nothing will read the queue —
            # resolve typed instead of hanging the stream
            lo._fail_queued(ServerClosed(
                "generation server drained while the request was "
                "being admitted"))
        return stream

    def generate(self, prompt_ids, **kw) -> List[int]:
        """Synchronous convenience: ``submit(...).result()``."""
        return self.submit(prompt_ids, **kw).result()

    # -- drain --------------------------------------------------------------

    def wait(self, poll_s: float = 0.1,
             timeout: Optional[float] = None) -> dict:
        t0 = time.monotonic()
        while not self._drain_event.is_set():
            if timeout is not None and time.monotonic() - t0 >= timeout:
                break
            core_health.beat()
            time.sleep(poll_s)
        return self.drain()

    def drain(self, timeout: float = 30.0) -> dict:
        """Graceful shutdown: stop admitting, flush every accepted
        stream — finish decoding what's owed within ``timeout``, fail
        the rest typed — and report. ``unaccounted`` (requests) and
        ``tokens_owed`` are both ≡ 0 by construction; the report proves
        it."""
        with self._admit_lock:
            self._accepting = False
            self._drain_event.set()
        drained = True
        if self._loop is not None:
            drained = self._loop.drained.wait(timeout)
            if not drained:
                self._loop.abort(DeadlineExceeded(
                    f"generation drain timed out after {timeout}s"))
                self._loop.drained.wait(max(timeout, 1.0))
            self._loop.join(timeout=max(timeout, 1.0))
            self._loop._fail_queued(ServerClosed(
                "generation server drained while the request was "
                "being admitted"))
        core_health.remove_drain_callback(self._drain_event.set)
        snap = self.metrics.snapshot()
        c = snap["counters"]
        report = {
            "drained": bool(drained),
            "fatal": (repr(self._loop.fatal) if self._loop is not None
                      and self._loop.fatal is not None else None),
            "accepted": (c.get("requests_total", 0)
                         - c.get("shed_total", 0)),
            "completed": c.get("streams_completed_total", 0),
            "deadline_failed": c.get("deadline_expired_total", 0),
            "cancelled": c.get("streams_cancelled_total", 0),
            "errors": c.get("errors_total", 0),
            "shed": c.get("shed_total", 0),
            "tokens_generated": c.get("tokens_generated_total", 0),
            "tokens_streamed": c.get("tokens_streamed_total", 0),
            "tokens_dropped": c.get("tokens_dropped_total", 0),
            "decode_compiles": self.engine.decode_compile_count,
            "decode_dispatches": self.engine.decode_dispatch_count,
            "prefill_compile_counts": dict(
                self.engine.prefill_compile_counts),
        }
        report["unaccounted"] = (
            report["accepted"] - report["completed"]
            - report["deadline_failed"] - report["cancelled"]
            - report["errors"])
        report["tokens_owed"] = (
            report["tokens_generated"] - report["tokens_streamed"]
            - report["tokens_dropped"])
        if self.engine.paged:
            # pages held by anything but the (intentionally warm)
            # prefix cache after drain = a leak; ≡ 0 by construction
            st = self.engine.pool.stats()
            report["kv_pages_owed"] = (
                st["pages_in_use"] - st["pages_cached"])
        return report

    stop = drain


class _GenerationLoop(threading.Thread):
    """The scheduler thread: admits prompts into free slots, runs one
    decode dispatch per iteration for every active slot, delivers
    tokens, enforces deadlines/budgets, and answers chaos."""

    _POLL_S = 0.02

    def __init__(self, engine: GenerationEngine,
                 q: "queue.Queue", metrics: ServingMetrics,
                 drain_event: threading.Event,
                 preempt: bool = False):
        super().__init__(name="p1t-generation-loop", daemon=True)
        self.engine = engine
        self.q = q
        self.metrics = metrics
        self.drain = drain_event
        self.drained = threading.Event()
        self.fatal: Optional[BaseException] = None
        self._abort_exc: Optional[BaseException] = None
        self._by_slot: Dict[int, _GenRequest] = {}
        self._free: List[int] = list(range(engine.slots))
        self._spec_proposed = 0
        self._spec_accepted = 0
        # KV-pressure graceful degradation (serve_gen_preempt)
        self._preempt = bool(preempt) and engine.paged
        self._ceiling = float(
            core_flags.flag("serve_gen_pressure_ceiling"))
        # admission-deferred (pressure-gated) requests, FIFO-preserving
        self._pending: collections.deque = collections.deque()
        # preempted/parked live streams awaiting replay re-admission
        self._parked: List[_GenRequest] = []
        # gen_page_pressure chaos: pages the scheduler itself holds
        self._chaos_pages: List[int] = []
        self._chaos_release_tick = 0
        self._tick = 0
        self._debug_refcount = bool(
            core_flags.flag("debug_kv_refcount"))

    def abort(self, exc: BaseException) -> None:
        """A drain that ran out of patience: fail everything still in
        flight typed at the next loop boundary."""
        self._abort_exc = exc

    # -- resolution helpers (single-threaded: only this thread calls) -------

    def _deliver(self, req: _GenRequest, tok: int) -> None:
        m = self.metrics
        m.counter("tokens_generated_total").inc()
        if req.stream._put(tok):
            m.counter("tokens_streamed_total").inc()
        else:
            m.counter("tokens_dropped_total").inc()
        req.n_generated += 1
        req.emitted.append(int(tok))

    def _finish(self, req: _GenRequest, reason: str,
                exc: Optional[BaseException] = None) -> None:
        if req.stream._finish(reason, exc):
            m = self.metrics
            if reason in ("eos", "length"):
                m.counter("streams_completed_total").inc()
                m.record_response()
            elif reason == "cancelled":
                m.counter("streams_cancelled_total").inc()
            elif reason in ("deadline", "budget"):
                m.counter("deadline_expired_total").inc()
            else:
                m.counter("errors_total").inc()
            fresh = req.n_generated - req.resumed
            if fresh > 0 and req.t_first:
                dt = max(time.monotonic() - req.t_first, 1e-9)
                m.histogram("tokens_per_s").observe(fresh / dt)
        if req.slot >= 0:
            self.engine.release(req.slot)
            import bisect
            bisect.insort(self._free, req.slot)
            del self._by_slot[req.slot]
            req.slot = -1

    def _fail_queued(self, exc: BaseException) -> None:
        while True:
            try:
                req = self.q.get_nowait()
            except queue.Empty:
                return
            if req.stream._finish("error", exc):
                self.metrics.counter("errors_total").inc()

    def _fail_inflight(self, exc: BaseException, reason="error") -> None:
        for slot in list(self._by_slot):
            self._finish(self._by_slot[slot], reason, exc)
        # parked (preempted) and pressure-deferred requests are owed a
        # typed answer too — they were accepted
        for req in self._parked:
            self._finish(req, reason, exc)
        self._parked = []
        while self._pending:
            self._finish(self._pending.popleft(), reason, exc)

    # -- scheduling ---------------------------------------------------------

    def _next_request(self) -> Optional[_GenRequest]:
        """Pressure-deferred requests re-try before fresh arrivals
        (FIFO is preserved: a deferral pushes back to the deque head)."""
        if self._pending:
            return self._pending.popleft()
        try:
            return self.q.get_nowait()
        except queue.Empty:
            return None

    def _admissible(self, req: _GenRequest) -> Optional[bool]:
        """Pressure gate (``serve_gen_preempt``): True = admit now,
        False = defer (the pool is too full — never a failure), None =
        this request could never fit the whole pool even alone (the
        ONLY admission shape that still fails typed)."""
        eng = self.engine
        if not self._preempt or not eng.paged:
            return True
        ps = eng.page_size
        orig_p = len(req.prompt) - req.resumed
        worst = min(-(-(orig_p + req.max_new) // ps),
                    eng.pages_per_slot)
        total = eng.pool.num_pages - 1
        if worst > total:
            return None
        pf = len(req.prompt) + len(req.emitted)
        need = (pf - 1) // ps + 1
        st = eng.pool.stats()
        if need > st["pages_free"] + st["pages_cached"]:
            return False  # not even eviction could serve the prefill
        live = st["pages_in_use"] - st["pages_cached"]
        if live > 0 and live + need > self._ceiling * total:
            return False  # defer: keep decode-growth headroom
        return True

    def _park(self, req: _GenRequest, why: str) -> None:
        """Preempt a live stream: release its pages THIS tick, park the
        request, re-admit later via the bit-identical replay path."""
        slot = req.slot
        self.engine.release(slot)
        import bisect
        bisect.insort(self._free, slot)
        del self._by_slot[slot]
        req.slot = -1
        req.spec = None
        req.preempted += 1
        self._parked.append(req)
        m = self.metrics
        m.counter("gen_preemptions_total").inc()
        m.gauge("gen_parked_streams").set(len(self._parked))
        obs_events.emit("gen_stream_preempt", slot=slot,
                        tokens=req.n_generated, priority=req.priority,
                        why=why)

    def _handle_fault(self, slot: int, exc: BaseException) -> None:
        """A decode page fault the pool could not serve. Preempt off:
        fail that stream typed (the PR 16 contract). Preempt on: shed
        pressure instead — the pool already LRU-evicted every cached
        prefix; now preempt strictly-lower-priority victims (longest
        deadline slack first) until the fault fits, else park the
        faulting stream itself. Nothing client-visible either way."""
        req = self._by_slot.get(slot)
        if req is None:
            return
        if not self._preempt:
            self._finish(req, "error", exc)
            return
        eng = self.engine
        need = max(
            (int(eng._host_len[slot]) + eng.window - 1)
            // eng.page_size + 1 - len(eng._slot_pages[slot]), 1)
        now = time.monotonic()

        def slack(r: _GenRequest) -> float:
            return float("inf") if r.deadline is None \
                else r.deadline - now
        victims = sorted(
            (r for s, r in self._by_slot.items()
             if s != slot and r.priority > req.priority),
            key=lambda r: (r.priority, slack(r)), reverse=True)
        while victims and eng.pool.free_pages < need:
            self._park(victims.pop(0),
                       "preempted by higher-priority page fault")
        if eng.pool.free_pages < need:
            # no (more) eligible victims: the faulting stream yields
            self._park(req, "parked under KV pressure")

    def _readmit_parked(self, now: float) -> None:
        """Re-admit parked streams (before fresh arrivals — they are
        older) from ``prompt + everything already emitted`` with the
        key chain advanced past it: the continuation is bit-identical
        to never having been preempted. Cancels/deadlines apply while
        parked too."""
        if not self._parked:
            return
        # snapshot: _admit_one can park a request straight back (pool
        # miss at prefill) — it lands on the emptied self._parked and
        # is merged below, never mutated under iteration
        work = self._parked
        self._parked = []
        keep: List[_GenRequest] = []
        for req in work:
            if req.stream._cancel_requested:
                self._finish(req, "cancelled", StreamCancelled(
                    f"cancelled after {req.n_generated} tokens "
                    "(while parked)"))
                continue
            if req.deadline is not None and now > req.deadline:
                self._finish(req, "deadline", DeadlineExceeded(
                    f"wall deadline exceeded after {req.n_generated} "
                    "tokens (while parked under KV pressure)"))
                continue
            if not self._free:
                keep.append(req)
                continue
            ok = self._admissible(req)
            if ok is None:
                self._finish(req, "error", KVPoolExhausted(
                    "parked stream can never fit the page pool alone "
                    "— raise serve_gen_kv_pages"))
                continue
            if not ok:
                keep.append(req)
                continue
            if self._admit_one(req, now):
                self.metrics.counter(
                    "gen_preempt_readmits_total").inc()
        self._parked = keep + self._parked
        self.metrics.gauge("gen_parked_streams").set(
            len(self._parked))

    def _admit_one(self, req: _GenRequest, now: float) -> bool:
        """Claim the lowest free slot and prefill (fresh admission and
        parked/resumed replay share this path)."""
        slot = self._free.pop(0)
        req.slot = slot
        self._by_slot[slot] = req
        prior = req.resumed + len(req.emitted)
        pp = req.prompt if not req.emitted else np.concatenate(
            [req.prompt, np.asarray(req.emitted, np.int32)])
        try:
            t0 = time.monotonic()
            first = self.engine.prefill(
                slot, pp, req.temperature, req.top_k, req.seed,
                start_index=prior)
            self.metrics.histogram("prefill_ms").observe(
                (time.monotonic() - t0) * 1e3)
            if not req.t_first:
                self.metrics.histogram("queue_ms").observe(
                    (t0 - req.t_enq) * 1e3)
        except KVPoolExhausted as e:
            # raced the admission estimate: under preemption park it
            # (never a client-visible failure); otherwise typed
            import bisect
            bisect.insort(self._free, slot)
            del self._by_slot[slot]
            req.slot = -1
            if self._preempt:
                req.preempted += 1
                self._parked.append(req)
                self.metrics.counter("gen_preemptions_total").inc()
                return False
            self._finish(req, "error", e)
            return False
        except Exception as e:
            self._finish(req, "error", e)
            return False
        if not req.t_first:
            req.t_first = time.monotonic()
        if self.engine.spec_tokens > 0:
            req.spec = NGramSpeculator(
                pp, self.engine.spec_tokens,
                n=int(core_flags.flag("serve_gen_spec_ngram")))
            req.spec.observe(first)
        self._deliver(req, first)
        self._maybe_complete(req, first)
        return True

    def _admit(self) -> None:
        """Claim free slots for queued prompts (iteration-level
        scheduling: runs between decode steps, so a late request joins
        the RUNNING batch). A drain keeps admitting — queued requests
        were accepted and are owed an answer — while `submit` has
        already stopped new arrivals. Under ``serve_gen_preempt``,
        parked streams re-admit first and fresh admissions are
        pressure-gated (deferred, never failed)."""
        now = time.monotonic()
        self._readmit_parked(now)
        while self._free:
            req = self._next_request()
            if req is None:
                return
            now = time.monotonic()
            if req.stream._cancel_requested:
                self._finish(req, "cancelled", StreamCancelled(
                    "cancelled before decoding started"))
                continue
            if req.deadline is not None and now > req.deadline:
                self._finish(req, "deadline", DeadlineExceeded(
                    f"request expired after "
                    f"{(now - req.t_enq) * 1e3:.1f}ms in queue — "
                    "never prefetched into a slot"))
                continue
            ok = self._admissible(req)
            if ok is None:
                self._finish(req, "error", KVPoolExhausted(
                    f"request needs more pages than the whole pool "
                    f"holds ({self.engine.pool.num_pages - 1} usable)"
                    " — raise serve_gen_kv_pages or lower "
                    "max_new_tokens"))
                continue
            if not ok:
                self._pending.appendleft(req)
                self.metrics.counter(
                    "gen_admission_deferrals_total").inc()
                return
            # lowest free slot first: deterministic assignment (chaos
            # specs name slots; staggered-parity runs reproduce)
            self._admit_one(req, now)

    def _maybe_complete(self, req: _GenRequest, tok: int) -> None:
        eos = self.engine.eos_id
        if eos is not None and tok == eos:
            self._finish(req, "eos")
        elif req.n_generated >= req.max_new:
            if req.truncated_by_budget:
                self._finish(req, "budget", DeadlineExceeded(
                    f"token budget exhausted after {req.n_generated} "
                    "tokens (server cap serve_gen_token_budget/"
                    "max_seq room below the requested "
                    "max_new_tokens) — stream truncated"))
            else:
                self._finish(req, "length")

    def _sweep(self) -> None:
        """Client cancels + wall deadlines, checked at step boundaries
        so a mid-stream failure is typed and immediate."""
        now = time.monotonic()
        for slot in list(self._by_slot):
            req = self._by_slot[slot]
            if req.stream._cancel_requested:
                self._finish(req, "cancelled", StreamCancelled(
                    f"cancelled after {req.n_generated} tokens"))
            elif req.deadline is not None and now > req.deadline:
                self._finish(req, "deadline", DeadlineExceeded(
                    f"wall deadline exceeded mid-stream after "
                    f"{req.n_generated} tokens"))

    # -- main loop ----------------------------------------------------------

    def run(self) -> None:  # hot-path: the decode loop
        m = self.metrics
        slots = self.engine.slots
        try:
            # hot section for the sanitizer's sync accounting: every
            # readback on this thread attributes to the decode loop
            with jit_sanitizer.hot_section("gen_decode_loop"):
                self._run_loop(m, slots)
        except BaseException as e:  # noqa: broad-except — the loop
            # thread must record ANY death and resolve every stream
            # typed rather than leave clients blocked mid-iteration
            self.fatal = e
            err = RuntimeError(f"generation loop died: {e!r}")
            self._fail_inflight(err)
            self._fail_queued(err)
            self.drain.set()
            try:
                core_health.report_unhealthy(
                    f"generation loop died: {e!r}")
            except Exception:  # noqa: broad-except — best-effort
                # marker; the fatal must not be masked by an
                # unwritable health dir
                pass
            if not isinstance(e, Exception):
                raise
        finally:
            self.drained.set()
            # close the admission race for good: a submit whose put
            # landed after this loop's final empty-queue check is
            # either swept HERE (put before the sweep) or sees
            # drained already set on its own post-put check (put
            # after the sweep — drained.set() above happened-before
            # it) and sweeps itself. Normal drains flushed the queue
            # already, so this is a no-op for them.
            self._fail_queued(ServerClosed(
                "generation server drained while the request was "
                "being admitted"))

    def _maybe_release_chaos_pages(self) -> None:
        """Let go of gen_page_pressure chaos holds once their tick
        window passed (or immediately under drain/abort, so parked
        streams can complete and kv_pages_owed lands at 0)."""
        if self._chaos_pages and (
                self._tick >= self._chaos_release_tick
                or self.drain.is_set() or self._abort_exc is not None):
            self.engine.pool.release(self._chaos_pages)
            self._chaos_pages = []

    def _run_loop(self, m, slots: int) -> None:  # hot-path: decode loop
        while True:
            core_health.beat()
            self._tick += 1
            if self.engine.paged:
                self._maybe_release_chaos_pages()
            if self._abort_exc is not None:
                self._fail_inflight(self._abort_exc)
                self._fail_queued(self._abort_exc)
                break
            self._sweep()
            self._admit()
            if not self._by_slot:
                m.gauge("slot_occupancy").set(0.0)
                if (self.drain.is_set() and self.q.empty()
                        and not self._parked and not self._pending):
                    break
                time.sleep(self._POLL_S)
                continue
            if (self.engine.paged and core_chaos.enabled()
                    and core_chaos.check_gen_pressure()):
                # claim every free page and squat for ~25 ticks: the
                # deterministic trigger for the preemption path
                free = self.engine.pool.free_pages
                if free:
                    self._chaos_pages.extend(
                        self.engine.pool.alloc(free))
                self._chaos_release_tick = self._tick + 25
                obs_events.emit("gen_page_pressure",
                                pages_held=len(self._chaos_pages))
            wedged, slow = core_chaos.check_gen_step(
                list(self._by_slot))
            if slow:
                time.sleep(float(
                    core_flags.flag("serve_chaos_slow_s")))
            if wedged is not None and wedged in self._by_slot:
                req = self._by_slot[wedged]
                self._finish(req, "error", SlotWedged(
                    f"decode slot {wedged} wedged after "
                    f"{req.n_generated} tokens (chaos "
                    "gen_slot_wedge) — stream failed, slot "
                    "released, cohabitants unaffected"))
            if not self._by_slot:
                continue
            active = np.zeros([slots], bool)
            for slot, req in self._by_slot.items():
                active[slot] = req.stream._writable()
            m.gauge("slot_occupancy").set(
                len(self._by_slot) / slots)
            if not active.any():
                time.sleep(self._POLL_S)  # every stream is parked
                continue
            eng = self.engine
            drafts = np.zeros([slots, eng.spec_tokens], np.int32)
            nd = np.zeros([slots], np.int32)
            if eng.spec_tokens > 0:
                for slot, req in self._by_slot.items():
                    if active[slot] and req.spec is not None:
                        d = req.spec.propose()
                        nd[slot] = d.size
                        drafts[slot, :d.size] = d
            t0 = time.monotonic()
            toks, flags = eng.decode(active, drafts, nd)
            dt = time.monotonic() - t0
            m.histogram("decode_step_ms").observe(dt * 1e3)
            # a page fault the pool could not serve, handled at this
            # step boundary (the slot was masked out of the dispatch;
            # cohabitants decoded normally): preempt off = fail THAT
            # stream typed; preempt on = shed pressure instead
            # (prefix cache already LRU-shed inside pool.alloc, then
            # lowest-priority/longest-deadline victim parks, else the
            # faulting stream itself parks) — never client-visible
            for slot, exc in eng.last_page_faults.items():
                self._handle_fault(slot, exc)
            from ..obs import trace as obs_trace
            if obs_trace.sink_active():
                # decode spans tag slot occupancy: the trace view
                # shows continuous batching fill alongside timing
                obs_trace.record_span(
                    "gen/decode_step", dt, cat="Serving",
                    args={"slots_active": int(active.sum()),
                          "occupancy": round(
                              len(self._by_slot) / slots, 4)})
            for slot in list(self._by_slot):
                if not active[slot]:
                    continue
                req = self._by_slot[slot]
                n_acc = int(flags[slot].sum())
                if eng.spec_tokens > 0 and nd[slot] > 0:
                    self._spec_proposed += int(nd[slot])
                    self._spec_accepted += max(n_acc - 1, 0)
                    m.counter("gen_spec_proposed_total").inc(
                        int(nd[slot]))
                    m.counter("gen_spec_accepted_total").inc(
                        max(n_acc - 1, 0))
                    m.gauge("gen_spec_accept_ratio").set(
                        self._spec_accepted
                        / max(self._spec_proposed, 1))
                # flags[slot] is a prefix: every accepted chain entry
                # is a real token, delivered in order; eos/length can
                # retire the request mid-window (extras are discarded
                # — the slot's pages release with it)
                for i in range(n_acc):
                    tok = int(toks[slot, i])
                    if req.spec is not None:
                        req.spec.observe(tok)
                    self._deliver(req, tok)
                    self._maybe_complete(req, tok)
                    if req.slot < 0:
                        break
            eng.publish_kv_metrics()
            if self._debug_refcount:
                # per-tick accounting sweep: sum-of-refcounts == refs
                # held by live slots + registry (+ chaos holds), typed
                # KVPageAccountingError AT the corrupting tick
                eng.check_kv_invariants(
                    extra_holders=(self._chaos_pages,))


# kept for parity tests/bench: eagerly decode ONE sequence with the
# concat-Cache path but the ENGINE's key schedule, so sampled outputs
# are comparable token-for-token with the jitted slot decode
def eager_generate(model, prompt_ids, max_new_tokens, eos_id=None,
                   temperature=0.0, top_k=0, seed=0):
    """Reference eager decode (one sequence, incremental concat cache):
    prefill the prompt, then sample a token per step with the same
    per-request key schedule the engine uses. Returns the token list."""
    import jax
    from ..core.tensor import to_tensor
    from ..nn.decode import sample_logits_array
    prompt = np.asarray(prompt_ids, np.int64).reshape(1, -1)
    cache = model.empty_cache(1)
    logits, cache = model(to_tensor(prompt), cache=cache)
    key = jax.random.fold_in(
        jax.random.key(int(seed) & 0x7FFFFFFF), 0)
    out: List[int] = []
    last = np.asarray(logits.numpy())[0, -1].astype(np.float32)
    for _ in range(int(max_new_tokens)):
        tok = int(np.asarray(sample_logits_array(
            last, jax.random.fold_in(key, 0),
            np.float32(temperature), np.int32(top_k))))
        key = jax.random.fold_in(key, 1)
        out.append(tok)
        if eos_id is not None and tok == eos_id:
            break
        if len(out) >= int(max_new_tokens):
            break
        ids = np.asarray([[tok]], np.int64)
        logits, cache = model(to_tensor(ids), cache=cache)
        last = np.asarray(logits.numpy())[0, -1].astype(np.float32)
    return out
