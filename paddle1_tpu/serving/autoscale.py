"""SLO-driven autoscaling: the actuator half of the control loop
(ISSUE 18; ROADMAP "close the loop").

PR 13 built the sensor layer — ``obs/slo.py`` burn-rate gauges, the
admission queue-depth EWMA, slot-occupancy / KV-page gauges — and PR
6/7/17 built every safe transition (supervised spawn/retire, elastic
``request_resize``, zero-downtime ``scale_to``). This module wires
sensor to actuator: an :class:`Autoscaler` evaluates those signals
against a declarative :class:`ScalingPolicy` and drives a
:class:`~paddle1_tpu.serving.fleet.ServingFleet`, a
:class:`~paddle1_tpu.serving.genfleet.GenerationFleet`, or (through
:class:`SupervisorTarget`) an elastic training world — through the
EXISTING surfaces only, so every transition keeps their contracts:
zero-downtime, ``unaccounted == 0``, bit-identical stream migration.

Control discipline (the anti-flap toolkit):

* **hysteresis bands** — scale-out above ``queue_hi``/``burn_hi``,
  scale-in only below the separate ``queue_lo``/``burn_lo``; the gap
  between them holds.
* **cooldown** — at least ``cooldown`` seconds between transitions.
* **scale-in dwell** — the calm condition must hold ``dwell`` seconds
  continuously before capacity is released (a flash crowd's trough
  must not shed the replicas the next spike needs).
* **typed backoff** — a refused or wedged transition
  (:class:`~paddle1_tpu.serving.errors.ScaleFailed`, a Supervisor
  :class:`~paddle1_tpu.distributed.supervisor.ResizeRefused`) parks
  the loop for ``backoff`` seconds with a typed journal record, then
  re-evaluates. The loop itself never crashes on a failed transition.
* **non-blocking actuation** — the background loop hands each
  transition to a single-flight worker thread and KEEPS SENSING: a
  replica spawn costs seconds (subprocess + jit warmup), and a loop
  that blocks on it is blind exactly when the flash crowd needs it.
  While a transition is in flight every tick resolves ``hold``
  ("transition in flight") but the hysteresis/dwell clocks still
  advance — calm observed while a scale-out spawns is valid evidence
  (capacity only increases), so the scale-in dwell earned during the
  spawn is not forfeited. Direct :meth:`Autoscaler.step` calls
  actuate INLINE so tests and benches stay deterministic.

Every decision emits a typed ``obs/events.py`` record
(``autoscale_decision`` / ``autoscale_refused``) and the
``autoscale_*`` metric families; decision latency lands in the
``autoscale_decision_seconds`` histogram so the <1%-overhead
acceptance gate is measurable, and with no Autoscaler constructed the
cost is structurally zero (no thread, no families).

For generative fleets, replica count IS the slot/page actuator:
every ``GenerationFleet`` replica carries its own decode-slot and KV
page pool (``serve_gen_slots`` / ``serve_gen_kv_pages``), so a
scale-out adds aggregate slot+page capacity without recompiling any
live replica's decode step (per-replica slot counts are baked into
the compiled decode signature — resizing them live would retrace).

Quickstart::

    policy = parse_policy("min=2;max=8;queue_hi=0.8;queue_lo=0.2;"
                          "burn_hi=1.0;cooldown=5;dwell=20")
    slos = obs_slo.parse_slos("lat=p99(e2e_ms)<50")
    scaler = Autoscaler(fleet, policy, slos=slos).start()
    ...                       # traffic; the loop scales the fleet
    scaler.stop()
"""

from __future__ import annotations

import dataclasses
import sys
import threading
import time
from typing import Dict, List, Optional

from ..core import flags as core_flags
from ..core.errors import InvalidArgumentError
from ..obs import events as obs_events
from .errors import ScaleFailed

__all__ = ["ScalingPolicy", "parse_policy", "Signals", "Decision",
           "Autoscaler", "SupervisorTarget"]

HOLD = "hold"
SCALE_OUT = "scale_out"
SCALE_IN = "scale_in"


@dataclasses.dataclass(frozen=True)
class ScalingPolicy:
    """Declarative scaling targets — what the loop holds, not how.

    Ratios are against capacity: ``queue_*`` bound the admission
    queue-depth EWMA over the fleet queue depth, ``burn_*`` bound the
    worst SLO burn-rate ratio (>1 = out of budget), ``occupancy_*``
    bound stream-slot occupancy (generative fleets), ``kv_free_min``
    is an absolute free-KV-page floor summed over live replicas (0
    disables the signal)."""
    min_replicas: int = 1
    max_replicas: int = 4
    queue_hi: float = 0.75
    queue_lo: float = 0.20
    burn_hi: float = 1.0
    burn_lo: float = 0.5
    occupancy_hi: float = 0.9
    occupancy_lo: float = 0.3
    kv_free_min: float = 0.0
    step: int = 1
    cooldown: float = 10.0
    dwell: float = 30.0
    backoff: float = 20.0
    interval: float = 1.0

    def __post_init__(self):
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise InvalidArgumentError(
                f"need 1 <= min ({self.min_replicas}) <= max "
                f"({self.max_replicas})")
        for lo, hi, what in ((self.queue_lo, self.queue_hi, "queue"),
                             (self.burn_lo, self.burn_hi, "burn"),
                             (self.occupancy_lo, self.occupancy_hi,
                              "occupancy")):
            if not 0 <= lo < hi:
                raise InvalidArgumentError(
                    f"{what} hysteresis band needs 0 <= lo < hi, got "
                    f"[{lo}, {hi}] — equal bounds flap on noise")
        if self.step < 1:
            raise InvalidArgumentError("step must be >= 1")
        for v, what in ((self.cooldown, "cooldown"),
                        (self.dwell, "dwell"),
                        (self.backoff, "backoff"),
                        (self.kv_free_min, "kv_free_min")):
            if v < 0:
                raise InvalidArgumentError(f"{what} must be >= 0")
        if self.interval <= 0:
            raise InvalidArgumentError("interval must be > 0")


_POLICY_KEYS = {
    "min": ("min_replicas", int), "max": ("max_replicas", int),
    "queue_hi": ("queue_hi", float), "queue_lo": ("queue_lo", float),
    "burn_hi": ("burn_hi", float), "burn_lo": ("burn_lo", float),
    "occ_hi": ("occupancy_hi", float), "occ_lo": ("occupancy_lo", float),
    "kv_free_min": ("kv_free_min", float),
    "step": ("step", int), "cooldown": ("cooldown", float),
    "dwell": ("dwell", float), "backoff": ("backoff", float),
    "interval": ("interval", float),
}


def parse_policy(spec: Optional[str] = None) -> ScalingPolicy:
    """Parse the ``serve_autoscale`` flag grammar —
    ``'min=2;max=8;queue_hi=0.8;queue_lo=0.2;burn_hi=1.0;burn_lo=0.5;
    occ_hi=0.9;occ_lo=0.3;kv_free_min=0;step=1;cooldown=10;dwell=30;
    backoff=20;interval=1'`` — every key optional, unknown keys and
    unparsable values are typed errors naming the clause."""
    if spec is None:
        spec = core_flags.flag("serve_autoscale")
    kw = {}
    for clause in str(spec).split(";"):
        clause = clause.strip()
        if not clause:
            continue
        key, sep, val = clause.partition("=")
        key = key.strip()
        if not sep or key not in _POLICY_KEYS:
            raise InvalidArgumentError(
                f"bad scaling-policy clause {clause!r} — keys: "
                f"{sorted(_POLICY_KEYS)}")
        field, conv = _POLICY_KEYS[key]
        try:
            kw[field] = conv(val.strip())
        except ValueError:
            raise InvalidArgumentError(
                f"bad scaling-policy value in {clause!r} "
                f"(expected {conv.__name__})") from None
    return ScalingPolicy(**kw)


@dataclasses.dataclass
class Signals:
    """One tick's sensor readings. ``None`` = the signal does not
    apply to this target (a serving fleet has no KV pages) — a signal
    that is absent can neither trigger nor veto a transition."""
    live: int = 0
    ready: int = 0
    queue_ratio: Optional[float] = None
    overload: Optional[float] = None
    burn_max: Optional[float] = None
    burns: Dict[str, float] = dataclasses.field(default_factory=dict)
    occupancy: Optional[float] = None
    kv_pages_free: Optional[float] = None


@dataclasses.dataclass(frozen=True)
class Decision:
    """One typed loop outcome: what (if anything) to do and why."""
    action: str                    # hold | scale_out | scale_in
    target: int                    # replica count the action aims at
    reason: str
    signals: Optional[Signals] = None


class SupervisorTarget:
    """Adapter presenting an elastic training world
    (:class:`~paddle1_tpu.distributed.supervisor.Supervisor`) as a
    scalable target: ``scale_to`` routes through ``request_resize``
    (the drain → reshard → relaunch path) and converts a typed
    :class:`ResizeRefused` into :class:`ScaleFailed` so the
    autoscaler's backoff discipline applies unchanged."""

    def __init__(self, supervisor):
        self._sup = supervisor

    def live_replicas(self) -> int:
        return int(self._sup.world_size or 0)

    def ready_replicas(self) -> int:
        return self.live_replicas()

    def scale_to(self, replicas: int,
                 ready_timeout_s: Optional[float] = None,
                 reason: str = "autoscale") -> dict:
        start = self.live_replicas()
        refusal = self._sup.request_resize(int(replicas), reason)
        if refusal is not None:
            raise ScaleFailed(str(refusal))
        return {"from": start, "to": int(replicas), "queued": True}


class Autoscaler:
    """The control loop. ``target`` is anything with
    ``scale_to(n, reason=...)`` / ``live_replicas()`` /
    ``ready_replicas()`` — both fleets qualify directly, a Supervisor
    via :class:`SupervisorTarget`. ``slos`` (an
    :class:`~paddle1_tpu.obs.slo.SloSet`) is evaluated against
    ``registry`` (default: the target's own metrics registry) each
    tick. Drive it with :meth:`start`/:meth:`stop` for the background
    loop, or call :meth:`step` directly for deterministic control
    (tests, benches)."""

    def __init__(self, target, policy: Optional[ScalingPolicy] = None,
                 slos=None, registry=None):
        self.target = target
        self.policy = policy if policy is not None else parse_policy()
        self.slos = slos
        self.registry = (registry if registry is not None
                         else getattr(target, "metrics", None))
        self._lock = threading.Lock()
        self._last_action_t: Optional[float] = None  # guarded-by: self._lock
        self._low_since: Optional[float] = None      # guarded-by: self._lock
        self._backoff_until = 0.0                    # guarded-by: self._lock
        self._last_refusal: Optional[str] = None     # guarded-by: self._lock
        self._decisions: List[Decision] = []         # guarded-by: self._lock
        self._inflight: Optional[tuple] = None       # guarded-by: self._lock
        self._stop_ev = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._actuator: Optional[threading.Thread] = None

    # -- sensors -----------------------------------------------------------

    def _peek_gauge(self, name: str) -> Optional[float]:
        reg = self.registry
        if reg is None:
            return None
        hit = reg.peek(name)
        if hit is None:
            return None
        kind, obj = hit
        return float(obj.value) if kind == "gauge" else None

    def collect(self) -> Signals:
        """Read every applicable sensor — peek-only against the
        registry (never materializes a family the target didn't
        publish: the structural-zero proof counts families)."""
        sig = Signals(live=int(self.target.live_replicas()),
                      ready=int(self.target.ready_replicas()))
        admission = getattr(self.target, "admission", None)
        if admission is not None:
            sig.queue_ratio = admission.ewma / max(1, admission.depth)
            sig.overload = admission.overload()
        else:
            # generative fleets: stream-slot occupancy is the queue
            # analog — active streams over aggregate slot capacity
            active = self._peek_gauge("gen_fleet_streams_active")
            per = getattr(self.target, "streams_per_replica", 0)
            if active is not None and per and sig.live:
                sig.occupancy = active / float(per * sig.live)
        kv_free = self._peek_gauge("gen_fleet_kv_pages_free")
        if kv_free is None:
            kv_free = self._peek_gauge("gen_kv_pages_free")
        sig.kv_pages_free = kv_free
        occ = self._peek_gauge("slot_occupancy")
        if occ is not None:
            sig.occupancy = occ
        if self.slos is not None:
            verdicts = self.slos.evaluate(self.registry, publish=True)
            sig.burns = {n: v["burn_rate"]
                         for n, v in verdicts.items()}
            sig.burn_max = max(sig.burns.values(), default=None)
        return sig

    # -- the decision ------------------------------------------------------

    def decide(self, sig: Signals, now: float) -> Decision:
        """Pure policy evaluation over one tick's signals (plus the
        loop's cooldown/dwell/backoff/in-flight clocks). Never
        actuates. While a transition is in flight the verdict is
        forced to ``hold`` — but the dwell/hysteresis clocks still
        advance, so calm observed during a slow scale-out spawn keeps
        counting toward the eventual scale-in."""
        p, cur = self.policy, sig.live
        with self._lock:
            backoff_until = self._backoff_until
            last_action = self._last_action_t
            low_since = self._low_since
            inflight = self._inflight
        d = self._evaluate(sig, now, backoff_until, last_action,
                           low_since)
        if inflight is not None:
            return Decision(
                HOLD, cur, f"transition in flight ({inflight[0]} -> "
                f"{inflight[1]} replicas); {d.reason}", sig)
        return d

    def _evaluate(self, sig: Signals, now: float,
                  backoff_until: float, last_action: Optional[float],
                  low_since: Optional[float]) -> Decision:
        p, cur = self.policy, sig.live
        if now < backoff_until:
            return Decision(HOLD, cur,
                            f"backoff after refused transition "
                            f"({backoff_until - now:.1f}s left)", sig)
        pressure = []
        if sig.burn_max is not None and sig.burn_max >= p.burn_hi:
            pressure.append(f"slo_burn {sig.burn_max:.2f} >= "
                            f"{p.burn_hi}")
        if sig.queue_ratio is not None and sig.queue_ratio >= p.queue_hi:
            pressure.append(f"queue_ewma {sig.queue_ratio:.2f} >= "
                            f"{p.queue_hi}")
        if sig.occupancy is not None and sig.occupancy >= p.occupancy_hi:
            pressure.append(f"occupancy {sig.occupancy:.2f} >= "
                            f"{p.occupancy_hi}")
        if p.kv_free_min > 0 and sig.kv_pages_free is not None \
                and sig.kv_pages_free <= p.kv_free_min:
            pressure.append(f"kv_pages_free {sig.kv_pages_free:.0f} "
                            f"<= {p.kv_free_min:.0f}")
        if pressure:
            with self._lock:
                self._low_since = None
            if last_action is not None \
                    and now - last_action < p.cooldown:
                return Decision(HOLD, cur, "cooldown under pressure: "
                                + "; ".join(pressure), sig)
            target = min(cur + p.step, p.max_replicas)
            if target <= cur:
                return Decision(HOLD, cur, "at max_replicas under "
                                "pressure: " + "; ".join(pressure),
                                sig)
            return Decision(SCALE_OUT, target, "; ".join(pressure), sig)
        calm = ((sig.burn_max is None or sig.burn_max < p.burn_lo)
                and (sig.queue_ratio is None
                     or sig.queue_ratio < p.queue_lo)
                and (sig.occupancy is None
                     or sig.occupancy < p.occupancy_lo))
        if not calm or cur <= p.min_replicas:
            with self._lock:
                self._low_since = None
            return Decision(HOLD, cur, "in band" if calm
                            else "between bands (hysteresis)", sig)
        if low_since is None:
            with self._lock:
                self._low_since = now
            return Decision(HOLD, cur,
                            f"calm — dwell 0.0/{p.dwell:.0f}s", sig)
        if now - low_since < p.dwell:
            return Decision(HOLD, cur,
                            f"calm — dwell {now - low_since:.1f}/"
                            f"{p.dwell:.0f}s", sig)
        if last_action is not None and now - last_action < p.cooldown:
            return Decision(HOLD, cur, "cooldown while calm", sig)
        target = max(cur - p.step, p.min_replicas)
        return Decision(SCALE_IN, target,
                        f"calm for {now - low_since:.0f}s", sig)

    # -- the loop ----------------------------------------------------------

    def step(self, now: Optional[float] = None,
             sync: bool = True) -> Decision:
        """One full tick: collect → decide → (maybe) actuate. With
        ``sync=True`` (the default — tests and benches) actuation runs
        INLINE and a refused or failed transition is reflected in the
        returned decision. The background loop passes ``sync=False``:
        the transition runs in a single-flight worker thread while
        subsequent ticks keep sensing (they resolve ``hold``
        "transition in flight"). Either way a refused transition is
        caught TYPED — counted, journaled, backoff armed — so the loop
        re-evaluates instead of crashing or flapping."""
        t0 = time.perf_counter()
        now = time.monotonic() if now is None else now
        m = self.registry
        sig = self.collect()
        decision = self.decide(sig, now)
        # decision latency stops HERE: actuation below blocks on
        # replica spawn/drain — that is capacity work the policy asked
        # for, not loop overhead, and timing it would make the <1%
        # acceptance gate unpassable by construction
        decide_s = time.perf_counter() - t0
        if m is not None:
            m.counter("autoscale_decisions_total").inc()
            if sig.queue_ratio is not None:
                m.gauge("autoscale_queue_ratio").set(
                    round(sig.queue_ratio, 4))
            if sig.burn_max is not None:
                m.gauge("autoscale_burn_max_ratio").set(sig.burn_max)
            m.gauge("autoscale_target_replicas").set(decision.target)
        if decision.action != HOLD:
            if sync:
                decision = self._actuate(decision, sig, now, t0,
                                         journal_refusal=False)
            else:
                with self._lock:
                    self._inflight = (decision.action, decision.target)
                worker = threading.Thread(
                    target=self._actuate,
                    args=(decision, sig, now, t0),
                    kwargs={"journal_refusal": True},
                    daemon=True, name="p1t-autoscale-actuate")
                self._actuator = worker
                worker.start()
        with self._lock:
            self._decisions.append(decision)
            del self._decisions[:-256]  # bounded decision journal
        if m is not None:
            m.histogram("autoscale_decision_seconds").observe(decide_s)
        return decision

    def _actuate(self, decision: Decision, sig: Signals,
                 launch_now: float, t_launch: float,
                 journal_refusal: bool) -> Decision:
        """Apply one transition through the target's own safe surface.
        Completion is stamped ``launch_now + real elapsed`` so cooldown
        starts when capacity actually changed — consistent whether the
        caller's clock is pinned (tests) or monotonic (the loop)."""
        m = self.registry
        try:
            report = self.target.scale_to(decision.target,
                                          reason=decision.reason)
            done_now = launch_now + (time.perf_counter() - t_launch)
            with self._lock:
                self._last_action_t = done_now
                if decision.action == SCALE_IN:
                    # calm observed at HIGHER capacity says nothing
                    # about the reduced fleet — the next scale-in must
                    # re-earn its dwell. A scale-out only ADDED
                    # capacity, so calm evidence accrued while it
                    # spawned stands.
                    self._low_since = None
            if m is not None:
                m.counter(f"autoscale_{decision.action}_total").inc()
            obs_events.emit(
                "autoscale_decision", action=decision.action,
                replicas_from=sig.live, replicas_to=decision.target,
                reason=decision.reason,
                applied=dict(report) if report else {})
            with self._lock:
                self._inflight = None
            return decision
        except Exception as e:  # noqa: broad-except — ScaleFailed is
            # the typed surface, but ANY wedged transition must park
            # the loop in backoff, not kill it
            done_now = launch_now + (time.perf_counter() - t_launch)
            with self._lock:
                self._backoff_until = done_now + self.policy.backoff
                self._last_refusal = str(e)
            if m is not None:
                m.counter("autoscale_refusals_total").inc()
            obs_events.emit(
                "autoscale_refused", action=decision.action,
                replicas_from=sig.live,
                replicas_to=decision.target,
                error=type(e).__name__, reason=str(e),
                backoff_s=self.policy.backoff)
            hold = Decision(HOLD, sig.live,
                            f"refused ({e}) — backoff "
                            f"{self.policy.backoff:.0f}s", sig)
            with self._lock:
                self._inflight = None
                if journal_refusal:
                    # the launch tick already journaled the attempt;
                    # record how it resolved
                    self._decisions.append(hold)
                    del self._decisions[:-256]
            return hold

    def decisions(self) -> List[Decision]:
        """The (bounded) in-memory decision journal, newest last."""
        with self._lock:
            return list(self._decisions)

    @property
    def last_refusal(self) -> Optional[str]:
        with self._lock:
            return self._last_refusal

    def start(self) -> "Autoscaler":
        if self._thread is not None:
            return self
        self._stop_ev.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="p1t-autoscale")
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop_ev.is_set():
            try:
                # async actuation: a multi-second replica spawn must
                # not blind the sensors mid-flash
                self.step(sync=False)
            except Exception as e:  # noqa: broad-except — the control
                # loop must survive a mid-teardown sensor race; a
                # broken tick is one skipped evaluation, not a dead
                # autoscaler
                print(f"autoscale tick error: {e!r}", file=sys.stderr)
            self._stop_ev.wait(self.policy.interval)

    def stop(self) -> None:
        self._stop_ev.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10.0)
        a, self._actuator = self._actuator, None
        if a is not None and a.is_alive():
            a.join(timeout=30.0)  # let an in-flight spawn land

    def __enter__(self) -> "Autoscaler":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False
