"""Serving metrics: counters + latency histograms with a plain-text dump.

The observability half of the serving runtime (ISSUE 4): every number a
load balancer / autoscaler / on-call needs to reason about a serving
worker — QPS, queue/pad/dispatch/readback latency quantiles, batch
occupancy, shed and deadline counts, per-bucket compile counts — lives
in one :class:`ServingMetrics` registry. ``snapshot()`` returns it as a
plain dict (JSON-able; the test/bench surface), ``render_text()`` emits
a Prometheus-style exposition for scraping.

Deliberately dependency-free and cheap: counters are a locked int,
histograms keep exact count/sum plus a bounded reservoir of recent
observations for quantiles (serving latency distributions are what the
last few thousand requests say, not what the process saw at boot). A
registry is instantiated per :class:`~paddle1_tpu.serving.Server`, so
two servers in one process (A/B models) never mix their numbers.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, Optional

__all__ = ["Counter", "Histogram", "ServingMetrics"]

# reservoir size per histogram: large enough for a stable p99 (the
# quantile of the last ~4k observations), small enough to sort per
# snapshot without showing up in a profile
_RESERVOIR = 4096
# QPS window: rate over the last N responses' timestamps
_QPS_WINDOW = 512


class Counter:
    """Monotone counter (requests, sheds, compiles...)."""

    __slots__ = ("name", "_v", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> int:
        return self._v


class Histogram:
    """Latency/occupancy histogram: exact count+sum, reservoir quantiles."""

    __slots__ = ("name", "_lock", "count", "sum", "max", "_recent")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self.count = 0
        self.sum = 0.0
        self.max = 0.0
        self._recent: collections.deque = collections.deque(
            maxlen=_RESERVOIR)

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            if v > self.max:
                self.max = v
            self._recent.append(v)

    def percentile(self, p: float) -> float:
        """Quantile over the reservoir (nearest-rank); 0.0 when empty."""
        with self._lock:
            data = sorted(self._recent)
        if not data:
            return 0.0
        idx = min(len(data) - 1, max(0, int(round(
            (p / 100.0) * (len(data) - 1)))))
        return data[idx]

    def summary(self) -> Dict[str, float]:
        with self._lock:
            data = sorted(self._recent)
            count, total, mx = self.count, self.sum, self.max
        def q(p):
            if not data:
                return 0.0
            return data[min(len(data) - 1,
                            max(0, int(round((p / 100.0)
                                             * (len(data) - 1)))))]
        return {"count": count, "sum": round(total, 4),
                "mean": round(total / count, 4) if count else 0.0,
                "p50": round(q(50), 4), "p95": round(q(95), 4),
                "p99": round(q(99), 4), "max": round(mx, 4)}


class ServingMetrics:
    """The per-server registry. Counters and histograms are created on
    first touch, so instrumentation points never need registration
    boilerplate and ``snapshot()`` only reports what actually fired."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._resp_times: collections.deque = collections.deque(
            maxlen=_QPS_WINDOW)
        self._started = time.monotonic()

    # -- instrumentation surface -------------------------------------------

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(name))
        return c

    def histogram(self, name: str) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            with self._lock:
                h = self._histograms.setdefault(name, Histogram(name))
        return h

    def record_response(self, n: int = 1) -> None:
        """Feed the QPS window (called once per completed request)."""
        now = time.monotonic()
        with self._lock:
            for _ in range(n):
                self._resp_times.append(now)

    def qps(self) -> float:
        """Responses/second over the recent-response window."""
        with self._lock:
            if len(self._resp_times) < 2:
                return 0.0
            span = self._resp_times[-1] - self._resp_times[0]
            n = len(self._resp_times) - 1
        if span <= 0:
            # burst faster than the clock tick: rate over process life
            span = max(time.monotonic() - self._started, 1e-6)
            n += 1
        return n / span

    # -- export surface -----------------------------------------------------

    def snapshot(self) -> Dict[str, object]:
        """The whole registry as one JSON-able dict."""
        with self._lock:
            counters = {n: c.value for n, c in self._counters.items()}
            hists = list(self._histograms.values())
        return {
            "qps": round(self.qps(), 2),
            "uptime_s": round(time.monotonic() - self._started, 3),
            "counters": counters,
            "histograms": {h.name: h.summary() for h in hists},
        }

    def render_text(self) -> str:
        """Prometheus-style plain-text exposition (one scrape page)."""
        snap = self.snapshot()
        lines = [f"p1t_serving_qps {snap['qps']}",
                 f"p1t_serving_uptime_seconds {snap['uptime_s']}"]
        for name, v in sorted(snap["counters"].items()):
            lines.append(f"p1t_serving_{name} {v}")
        for name, s in sorted(snap["histograms"].items()):
            for stat in ("count", "sum", "mean", "p50", "p95", "p99",
                         "max"):
                lines.append(f"p1t_serving_{name}_{stat} {s[stat]}")
        return "\n".join(lines) + "\n"
