"""Serving metrics — re-export shim over the unified registry.

The Counter/Gauge/Histogram/registry implementation that started life
here (ISSUE 4, grown through ISSUES 7/8) was promoted to
:mod:`paddle1_tpu.obs.registry` as the process-wide metrics layer
(ISSUE 10): one implementation, every subsystem. This module keeps the
serving-facing surface byte-compatible — :class:`ServingMetrics` is the
same class (namespace ``p1t_serving``, so every existing scrape page,
snapshot key and drain report is unchanged), :class:`MetricsGroup` and
:func:`merge_snapshots` are the same objects.

New code should import from ``paddle1_tpu.obs`` directly.
"""

from __future__ import annotations

from ..obs.registry import (_QPS_WINDOW, _RESERVOIR, Counter, Gauge,
                            Histogram, MetricsGroup, MetricsRegistry,
                            ServingMetrics, merge_snapshots,
                            render_snapshot_text)

__all__ = ["Counter", "Gauge", "Histogram", "ServingMetrics",
           "MetricsRegistry", "MetricsGroup", "merge_snapshots",
           "render_snapshot_text"]
