"""Serving replica worker: the subprocess half of the ServingFleet.

``python -m paddle1_tpu.serving.replica`` is what the fleet's
Supervisor spawns per replica rank: it loads one model, wraps it in a
:class:`~paddle1_tpu.serving.Server` (micro-batching, admission
control, deadlines — the whole PR 4 stack), binds a loopback socket,
publishes its endpoint, and serves framed requests from the fleet
dispatcher until a drain is requested.

Order of operations matters and is load-bearing:

1. ``health.beat()`` runs FIRST — it adopts the Supervisor's heartbeat
   channel and **pops** the ``PADDLE_FT_*`` env vars, so nothing this
   process later spawns (XLA compile helpers, user model code shelling
   out) can inherit the channel and mask a replica hang by beating its
   file (the PR 3 grandchild gotcha, re-tested for replicas).
2. Chaos arms only in incarnation 0: a Supervisor-restarted replica
   replays clean — the same fire-once contract as every other point.
3. The endpoint file is written AFTER the server started (and warmed,
   when configured): publishing the port is the ready signal, so the
   fleet's ready-handshake doubles as a health gate — a replica that
   dies in import/compile never publishes and the spawn times out
   typed.

SIGTERM (Supervisor drain/retire) unwinds through the Server's drain
protocol: stop admitting, flush every accepted request (complete or
typed), answer everything still owed on the wire, exit 0 — the fleet
sees a clean exit, never a failure.
"""

from __future__ import annotations

import argparse
import importlib
import importlib.util
import json
import os
import queue
import signal
import socket
import sys
import threading
import time
from typing import Optional

__all__ = ["main", "load_model"]

# resolver threads turning resolved ServeFutures into response frames;
# the Batcher does the real batching, so a small pool just overlaps the
# per-batch readback with frame writes
_RESOLVERS = 4


def load_model(spec: str, arg: str = ""):
    """Resolve a model spec into something InferenceEngine accepts.

    * ``/path/to/factory.py:fn`` — load the file as a module, call
      ``fn(arg)`` (how tests/bench ship deterministic toy models).
    * ``package.module:fn`` — import and call ``fn(arg)``.
    * ``artifact:/path/prefix`` — ``jit.load`` a saved inference
      artifact (the deployment path; ``arg`` is ignored).
    """
    if spec.startswith("artifact:"):
        from ..jit import load as jit_load
        return jit_load(spec[len("artifact:"):])
    mod_spec, sep, attr = spec.rpartition(":")
    if not sep:
        raise ValueError(
            f"model spec {spec!r} must be 'file.py:factory', "
            "'module:factory', or 'artifact:/path'")
    if mod_spec.endswith(".py"):
        modname = "_p1t_replica_model"
        m_spec = importlib.util.spec_from_file_location(modname, mod_spec)
        if m_spec is None or m_spec.loader is None:
            raise ValueError(f"cannot load model file {mod_spec!r}")
        module = importlib.util.module_from_spec(m_spec)
        sys.modules[modname] = module
        m_spec.loader.exec_module(module)
    else:
        module = importlib.import_module(mod_spec)
    return getattr(module, attr)(arg)


def _write_endpoint(path: str, payload: dict) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)  # atomic: the fleet never reads a torn file


class _DrainRequested(Exception):
    """Internal: aborts a blocking frame read when a drain arrived."""


def _resolver_loop(q: "queue.Queue", version: str) -> None:
    from . import wire
    from ..obs import trace as obs_trace
    while True:
        item = q.get()
        try:
            if item is None:
                return
            rid, fut, conn, send_lock, tr = item
            try:
                outs = fut.result()
            except Exception as e:  # noqa: broad-except — every owed
                # response must go back on the wire typed; the fleet
                # maps the error name back to its class
                header = {"kind": "error", "id": rid, "version": version,
                          "etype": type(e).__name__, "msg": str(e)}
                arrays = []
            else:
                arrays = outs if isinstance(outs, list) else [outs]
                header = {"kind": "result", "id": rid, "version": version}
            if tr is not None:
                # the request's span in THIS process: opened at submit
                # (another thread), closed here at resolution — parented
                # to the fleet's dispatch span from the wire header
                ctx, sid, t0 = tr
                obs_trace.record_span("replica/serve", time.time() - t0,
                                      ctx=ctx, span_id=sid,
                                      cat="Serving",
                                      args={"id": rid,
                                            "version": version})
            try:
                with send_lock:
                    wire.send_msg(conn, header, arrays)  # noqa: lock-blocking — lock is FOR sendall
            except (OSError, ConnectionError):
                pass  # fleet connection died; its failover retries this
        finally:
            q.task_done()


def _serve_conn(conn: socket.socket, srv, args, resolver_q,
                core_chaos, core_flags, core_health) -> None:
    """Pump one fleet connection until EOF or drain."""
    from . import wire
    conn.settimeout(0.25)
    send_lock = threading.Lock()

    def idle():
        core_health.beat()
        if core_health.drain_requested():
            raise _DrainRequested

    while True:
        try:
            header, arrays = wire.recv_msg(conn, idle=idle)
        except (ConnectionError, OSError):
            return  # fleet reconnects (or is gone for good)
        kind = header.get("kind")
        if kind == "ping":
            with send_lock:
                wire.send_msg(conn, {  # noqa: lock-blocking — frame lock IS for sendall
                    "kind": "pong", "id": header.get("id"),
                    "version": args.version,
                    "warm_buckets": sorted(srv.engine.compile_counts)})
        elif kind == "metrics":
            with send_lock:
                wire.send_msg(conn, {  # noqa: lock-blocking — frame lock IS for sendall
                    "kind": "metrics_result", "id": header.get("id"),
                    "version": args.version,
                    "snapshot": srv.metrics.snapshot()})
        elif kind == "infer":
            from ..obs import trace as obs_trace
            tr = None
            wire_ctx = obs_trace.adopt_header(header.get("trace"))
            if wire_ctx is not None and obs_trace.sink_active():
                # receipt marker FIRST — flushed before the chaos check
                # below can kill/wedge this process, so a request that
                # dies here is still visible in the merged trace (the
                # failover's "it reached replica N" evidence)
                obs_trace.instant("replica/recv", ctx=wire_ctx,
                                  cat="Serving",
                                  args={"id": header.get("id"),
                                        "rank": args.rank})
                tr = (wire_ctx, obs_trace.new_span_id(), time.time())
            if core_chaos.enabled():
                point = core_chaos.check_replica(args.rank)
                if point == core_chaos.REPLICA_KILL:
                    # an ungraceful death mid-request: no cleanup —
                    # the fleet must fail over the in-flight work
                    os.kill(os.getpid(), signal.SIGKILL)
                elif point == core_chaos.REPLICA_HANG:
                    # wedged RPC plane: stop reading forever while the
                    # Batcher keeps heartbeating — only the fleet's
                    # transport timeout + breaker can catch this
                    while True:  # pragma: no cover - exits via SIGKILL
                        time.sleep(3600)
                elif point == core_chaos.REPLICA_SLOW:
                    time.sleep(float(
                        core_flags.flag("serve_chaos_slow_s")))
            try:
                if tr is not None:
                    # submit under the request's context so the Server
                    # stamps it onto the batcher request (the dispatch
                    # span flow-links back to replica/serve)
                    with obs_trace.context(tr[0][0], tr[1]):
                        fut = srv.submit(
                            *arrays,
                            deadline_ms=header.get("deadline_ms"))
                else:
                    fut = srv.submit(
                        *arrays, deadline_ms=header.get("deadline_ms"))
            except Exception as e:  # noqa: broad-except — admission
                # errors (shed/closed/invalid) go back typed so the
                # fleet can retry elsewhere or surface them
                with send_lock:
                    wire.send_msg(conn, {  # noqa: lock-blocking — frame lock IS for sendall
                        "kind": "error", "id": header.get("id"),
                        "version": args.version,
                        "etype": type(e).__name__, "msg": str(e)})
                continue
            resolver_q.put((header.get("id"), fut, conn, send_lock, tr))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="paddle1_tpu serving replica worker")
    ap.add_argument("--endpoint-file", required=True)
    ap.add_argument("--model", required=True,
                    help="'file.py:factory', 'module:factory', or "
                         "'artifact:/path'")
    ap.add_argument("--model-arg", default="")
    ap.add_argument("--version", default="v0")
    ap.add_argument("--rank", type=int, default=0)
    ap.add_argument("--chaos", default="",
                    help="chaos spec armed in THIS process "
                         "(incarnation 0 only)")
    ap.add_argument("--server-config", default="{}",
                    help="JSON kwargs for serving.Server")
    args = ap.parse_args(argv)

    from ..core import chaos as core_chaos
    from ..core import flags as core_flags
    from ..core import health as core_health

    # 1. adopt the heartbeat channel (pops PADDLE_FT_* before anything
    #    else can snapshot the env for grandchildren)
    core_health.beat()
    # 2. chaos replays clean in restarted lives
    if args.chaos and core_health.incarnation() == 0:
        core_chaos.configure(args.chaos)

    from .server import Server

    model = load_model(args.model, args.model_arg)
    cfg = json.loads(args.server_config or "{}")
    if cfg.get("input_specs"):
        cfg["input_specs"] = [(tuple(s), d) for s, d in
                              cfg["input_specs"]]
    srv = Server(model, **cfg)
    srv.start()

    lst = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lst.bind(("127.0.0.1", 0))
    lst.listen(4)
    lst.settimeout(0.25)
    port = lst.getsockname()[1]
    # 3. publishing the endpoint IS the ready signal: server started
    #    (and warmed when configured) before the fleet can route here
    _write_endpoint(args.endpoint_file, {
        "port": port, "pid": os.getpid(), "rank": args.rank,
        "version": args.version,
        "incarnation": core_health.incarnation()})
    print(f"replica rank={args.rank} version={args.version} "
          f"serving on 127.0.0.1:{port}", flush=True)

    resolver_q: "queue.Queue" = queue.Queue()
    resolvers = [threading.Thread(target=_resolver_loop,
                                  args=(resolver_q, args.version),
                                  daemon=True, name=f"p1t-resolver-{i}")
                 for i in range(_RESOLVERS)]
    for t in resolvers:
        t.start()

    try:
        while not core_health.drain_requested():
            core_health.beat()
            try:
                conn, _ = lst.accept()
            except socket.timeout:
                continue
            try:
                _serve_conn(conn, srv, args, resolver_q, core_chaos,
                            core_flags, core_health)
            except _DrainRequested:
                break
    finally:
        lst.close()
    # graceful drain: flush every accepted request (Server.drain fails
    # anything wedged typed after its timeout, so the resolvers below
    # always terminate), answer everything owed, exit clean
    report = srv.drain()
    resolver_q.join()
    print(f"replica rank={args.rank} drained: "
          f"{json.dumps({k: v for k, v in report.items() if k != 'compile_counts'})}",
          flush=True)
    return 0 if report["unaccounted"] == 0 else 3


if __name__ == "__main__":
    sys.exit(main())
