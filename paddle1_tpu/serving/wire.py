"""Framed numpy-over-socket wire protocol for the serving fleet.

The :class:`~paddle1_tpu.serving.fleet.ServingFleet` front end and its
replica subprocesses speak length-prefixed frames over a loopback TCP
connection::

    u32 header_len | UTF-8 JSON header | per array: u32 npy_len | npy

Arrays ride as ``numpy.lib.format`` payloads with ``allow_pickle=False``
on BOTH ends — the same no-executable-payloads rule ``fluid.io`` adopted
for checkpoints (PR 4): a serving fleet is long-lived infrastructure and
its IPC plane must not be a pickle deserializer, even on loopback. The
JSON header carries everything else (request id, kind, version tag,
deadline, error type/message — and, when tracing is on, the request's
``trace`` context ``{"t": trace_id, "s": parent_span_id}`` from
:mod:`paddle1_tpu.obs.trace`, which is how one chrome trace follows a
request across the fleet/replica process boundary).

Reads are restartable across socket timeouts: :func:`recv_msg` keeps
its partial buffer while the caller's ``idle`` hook runs (the replica
checks for a drain request there; the fleet receiver checks for
shutdown), so a timeout can never desynchronize the frame stream — only
a peer close (``ConnectionError``) or the hook raising aborts a read.
With NO ``idle`` hook a socket timeout propagates (``socket.timeout``
is an ``OSError``): the socket's own timeout is then the caller's read
deadline — the fleet's connect handshake relies on this to bound a
ping against a replica that accepted the connection but never answers.

Streaming extension (the GenerationFleet's token plane): long-lived
token streams ride the SAME framed protocol as header-only frames —
:func:`send_stream_tokens` carries ``{kind: "tokens", id, seq, toks}``
where ``seq`` is the MONOTONE absolute index (from 0) of ``toks[0]``
within its stream, and :func:`send_stream_end` closes a stream with
its finish reason and total count. The sequence number is the
exactly-once contract: the fleet accepts a token iff its seq equals
the count already received, drops duplicates (< — a failover replay or
retire-migration race re-sending what the client has), and treats a
gap (>) as a desynced replica to fail over from. Because ``recv_msg``
is restartable across socket timeouts, a quiet stream never
desynchronizes the frame plane — stream frames interleave freely with
pong/metrics replies on one connection.
"""

from __future__ import annotations

import io
import json
import socket
import struct
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.locks import note_blocking

__all__ = ["send_msg", "recv_msg", "send_stream_tokens",
           "send_stream_end", "STREAM_TOKENS", "STREAM_END"]

_U32 = struct.Struct("<I")
# a header is a small JSON dict; anything bigger is a desynced stream,
# not a real frame — fail loudly instead of allocating garbage lengths
_MAX_HEADER = 1 << 20
# per-array bound for the same reason: 4 garbage bytes landing on an
# array-length slot must raise, not pre-allocate a ~4 GiB recv buffer
# (1 GiB comfortably covers any real request batch)
_MAX_ARRAY = 1 << 30


def send_msg(sock: socket.socket, header: Dict[str, object],
             arrays: Sequence[np.ndarray] = ()) -> None:
    """Write one frame (header dict + arrays). The caller serializes
    concurrent senders (a per-connection send lock): ``sendall`` of one
    pre-assembled buffer keeps the frame atomic on the wire."""
    blobs: List[bytes] = []
    for a in arrays:
        buf = io.BytesIO()
        np.lib.format.write_array(buf, np.ascontiguousarray(a),
                                  allow_pickle=False)
        blobs.append(buf.getvalue())
    h = dict(header)
    h["n"] = len(blobs)
    hb = json.dumps(h, separators=(",", ":")).encode("utf-8")
    out = bytearray(_U32.pack(len(hb)))
    out += hb
    for b in blobs:
        out += _U32.pack(len(b))
        out += b
    sock.sendall(bytes(out))


STREAM_TOKENS = "tokens"
STREAM_END = "stream_end"


def send_stream_tokens(sock: socket.socket, stream_id: int, seq: int,
                       toks: Sequence[int]) -> None:
    """One per-token stream frame: ``toks[i]`` is token ``seq + i`` of
    stream ``stream_id`` (seq = absolute monotone index from 0, the
    receiver's exactly-once dedup key). Header-only — token ids are
    small ints, so JSON beats an npy blob here. Same caller-holds-the-
    send-lock contract as :func:`send_msg`."""
    send_msg(sock, {"kind": STREAM_TOKENS, "id": int(stream_id),
                    "seq": int(seq),
                    "toks": [int(t) for t in toks]})


def send_stream_end(sock: socket.socket, stream_id: int, n: int,
                    reason: str, etype: Optional[str] = None,
                    msg: str = "") -> None:
    """Close stream ``stream_id``: ``n`` = total tokens emitted (the
    receiver cross-checks it against its own count), ``reason`` = the
    TokenStream finish reason, ``etype``/``msg`` carry the typed error
    for non-clean reasons. The count rides as ``"count"`` — ``"n"`` is
    the frame protocol's array-count slot and :func:`send_msg` owns it.
    Same send-lock contract as :func:`send_msg`."""
    send_msg(sock, {"kind": STREAM_END, "id": int(stream_id),
                    "count": int(n), "reason": str(reason),
                    "etype": etype, "msg": str(msg)})


def _recv_exact(sock: socket.socket, n: int, idle=None) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            # no hook: the socket timeout IS the caller's deadline —
            # propagate rather than spin forever on a silent peer
            if idle is None:
                raise
            # partial frame preserved in ``buf`` — the hook may raise
            # (drain/shutdown) to abort, else we keep waiting
            idle()
            continue
        if not chunk:
            raise ConnectionError(
                "peer closed mid-frame" if buf else "peer closed")
        buf += chunk
    return bytes(buf)


def recv_msg(sock: socket.socket, idle=None
             ) -> Tuple[Dict[str, object], List[np.ndarray]]:
    """Read one frame; returns ``(header, arrays)``. Raises
    ``ConnectionError`` when the peer closed (mid-frame or between
    frames); ``idle()`` runs on every socket timeout and may raise to
    abort the read."""
    # sanitizer hook: a frame read can block for the peer's whole
    # compute; doing that while holding a sanitized lock stalls every
    # thread needing it (free no-op unless debug_lock_sanitizer armed)
    note_blocking("wire.recv_msg")
    (hlen,) = _U32.unpack(_recv_exact(sock, 4, idle))
    if hlen > _MAX_HEADER:
        raise ConnectionError(
            f"frame header claims {hlen} bytes — desynchronized stream")
    header = json.loads(_recv_exact(sock, hlen, idle).decode("utf-8"))
    arrays: List[np.ndarray] = []
    for _ in range(int(header.get("n", 0))):
        (alen,) = _U32.unpack(_recv_exact(sock, 4, idle))
        if alen > _MAX_ARRAY:
            raise ConnectionError(
                f"frame array claims {alen} bytes — desynchronized "
                "stream")
        arrays.append(np.lib.format.read_array(
            io.BytesIO(_recv_exact(sock, alen, idle)),
            allow_pickle=False))
    return header, arrays
