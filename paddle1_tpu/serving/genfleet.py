"""Fault-tolerant generative serving: supervised generation replicas,
bit-identical mid-stream failover, KV-pressure-aware routing (ISSUE 17).

One :class:`~paddle1_tpu.serving.GenerationServer` process is a single
point of failure with much more to lose than a batch-inference Server:
a replica death doesn't just drop a request-response pair, it kills
every *long-lived token stream* mid-flight. :class:`GenerationFleet`
is the HA layer — the generative sibling of PR 7's
:class:`~paddle1_tpu.serving.fleet.ServingFleet`, built from the same
pieces (Supervisor via ``supervise_once``, endpoint-file ready
handshake, health-gated rotation, circuit breakers) with two new
mechanisms the streaming shape demands:

* **Mid-stream failover, bit-identical.** Token streams ride the
  framed wire protocol as per-token frames carrying a monotone
  absolute sequence number (:func:`~.wire.send_stream_tokens`). When a
  replica dies (transport EOF), wedges (live streams but no frames for
  ``serve_gen_stream_timeout_ms``), or trips its breaker, every
  in-flight stream is re-admitted on a survivor from ``prompt + tokens
  already received`` with the SAME seed and the next token index — the
  engine's counter-based RNG schedule (``resume_key``) makes the
  continuation bit-identical to the uninterrupted run, greedy and
  sampled alike. The sequence number is the exactly-once contract: a
  frame is accepted iff its seq equals the count already delivered,
  duplicates (a replay overlap, a retire race) are dropped, and a gap
  marks the replica desynced — failover, not corruption. The typed
  :class:`~.errors.StreamFailed` surfaces only when ``serve_retry_max``
  re-admissions exhaust; a successful failover is invisible.

* **KV-pressure-aware routing.** Replicas report their page-pool
  occupancy in every pong; the fleet's pullers prefer not to place a
  stream whose worst-case page footprint exceeds a replica's free
  pages (the gate relaxes once the queue head has aged — the replica's
  own preemption machinery under ``serve_gen_preempt`` is the real
  backstop, parking low-priority streams instead of raising
  ``KVPoolExhausted``). ``priority`` rides the wire so replica-side
  preemption ranks fleet traffic correctly.

Zero-downtime hot-swap carries over with one streaming twist:
:meth:`deploy` migrates a retiring replica's live streams by the same
replay path (no retry budget charged — migration is policy, not
failure), so a model roll never kills a stream either.

Quickstart::

    fleet = GenerationFleet("models/factory.py:make", replicas=3,
                            version="v1", slots=4, max_seq=128,
                            paged=True, pages=64).start()
    stream = fleet.submit([1, 2, 3], max_new_tokens=32,
                          temperature=0.8, seed=7)
    for tok in stream: ...       # exactly-once, failover-transparent
    report = fleet.drain()       # unaccounted == 0
"""

from __future__ import annotations

import collections
import json
import os
import socket
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core import chaos as core_chaos
from ..core import flags as core_flags
from ..core import health as core_health
from ..core import locks
from ..core.errors import InvalidArgumentError, PreconditionNotMetError
from ..obs import events as obs_events
from . import wire
from .errors import (DeadlineExceeded, DeployFailed, ScaleFailed,
                     ServerClosed, ServerOverloaded, StreamCancelled,
                     StreamFailed)
from .metrics import ServingMetrics

__all__ = ["GenerationFleet", "FleetStream"]

# stream_end error types that mean "place this stream elsewhere" with
# no evidence the replica is broken: it refused admission (shed /
# draining), its page pool genuinely couldn't hold the stream, or one
# slot wedged (per-request poison, engine healthy)
_FAILOVER_ETYPES = frozenset({"ServerOverloaded", "ServerClosed",
                              "KVPoolExhausted", "SlotWedged"})
# stream_end error types that are the CLIENT's outcome — surface typed,
# never failover, never feed the breaker
_CLIENT_ETYPES = frozenset({"DeadlineExceeded", "InvalidArgumentError",
                            "StreamCancelled"})


class FleetStream:
    """Client handle to one fleet-managed token stream: iterate tokens
    as they arrive, ``result()`` for the full list, ``cancel()`` to
    stop. Fed by the fleet's receiver threads with exactly-once
    dedup — across any number of failovers, token ``i`` is delivered
    once, and the sequence is bit-identical to an uninterrupted run.

    ``finish_reason`` mirrors :class:`~.generate.TokenStream`
    (``"eos"``/``"length"``/``"deadline"``/``"budget"``/
    ``"cancelled"``/``"error"``) plus ``"failed"`` when every failover
    retry exhausted (typed :class:`StreamFailed` via ``result()``/
    iteration)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._all: List[int] = []
        self._cursor = 0
        self._done = False
        self._exc: Optional[BaseException] = None
        self._cancel_requested = False
        self._cancel_cb = None   # fleet hook, set at submit
        self.finish_reason: Optional[str] = None

    # -- fleet side ---------------------------------------------------------

    def _count(self) -> int:
        with self._cond:
            return len(self._all)

    def _feed(self, seq: int, toks: Sequence[int]) -> str:
        """Accept a token frame under the exactly-once contract:
        ``'ok'`` (>=1 fresh token appended), ``'dup'`` (everything
        already delivered — dropped), ``'gap'`` (seq beyond the next
        expected index: the sender is desynced, fail over)."""
        with self._cond:
            if self._done:
                return "dup"  # late frame from a finished stream
            n = len(self._all)
            if seq > n:
                return "gap"
            if seq + len(toks) <= n:
                return "dup"
            self._all.extend(int(t) for t in toks[n - seq:])
            self._cond.notify_all()
            return "ok"

    def _finish(self, reason: str,
                exc: Optional[BaseException] = None) -> bool:
        with self._cond:
            if self._done:
                return False
            self._done = True
            self.finish_reason = reason
            self._exc = exc
            self._cond.notify_all()
        return True

    # -- client side --------------------------------------------------------

    def cancel(self) -> None:
        """Stop the stream: the owning replica releases its slot at the
        next step boundary; no further tokens. Idempotent."""
        with self._cond:
            if self._done or self._cancel_requested:
                return
            self._cancel_requested = True
        cb = self._cancel_cb
        if cb is not None:
            cb(self)

    def done(self) -> bool:
        return self._done

    @property
    def tokens(self) -> List[int]:
        """Every token delivered so far (a snapshot copy)."""
        with self._cond:
            return list(self._all)

    def __iter__(self) -> "FleetStream":
        return self

    def __next__(self) -> int:
        with self._cond:
            while True:
                if self._cursor < len(self._all):
                    tok = self._all[self._cursor]
                    self._cursor += 1
                    return tok
                if self._done:
                    if self._exc is not None and \
                            self.finish_reason != "cancelled":
                        raise self._exc
                    raise StopIteration
                self._cond.wait()

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the stream finishes; the full token list. Raises
        the stream's typed error (incl. :class:`StreamCancelled` after
        a cancel) — partial tokens stay readable via :attr:`tokens`."""
        with self._cond:
            deadline = (time.monotonic() + timeout
                        if timeout is not None else None)
            while not self._done:
                rem = None if deadline is None \
                    else deadline - time.monotonic()
                if rem is not None and rem <= 0:
                    raise DeadlineExceeded(
                        f"FleetStream not finished within {timeout}s — "
                        "the stream is still decoding (reader deadline "
                        "only; the stream stays accounted)")
                self._cond.wait(rem)
            if self._exc is not None:
                raise self._exc
            return list(self._all)


class _GenStreamReq:
    __slots__ = ("id", "prompt", "max_new", "temperature", "top_k",
                 "seed", "deadline", "deadline_ms", "priority",
                 "stream", "t_enq", "retries", "pinned", "owner")

    def __init__(self, rid: int, prompt: np.ndarray, max_new: int,
                 temperature: float, top_k: int, seed: int,
                 deadline_s: Optional[float],
                 deadline_ms: Optional[float], priority: int,
                 pinned: bool = False):
        self.id = rid
        self.prompt = prompt
        self.max_new = int(max_new)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.seed = int(seed)
        self.t_enq = time.monotonic()
        self.deadline = (self.t_enq + deadline_s
                         if deadline_s is not None else None)
        self.deadline_ms = deadline_ms
        self.priority = int(priority)
        self.stream = FleetStream()
        self.retries = 0
        # pinned = must be served by the replica it was sent to (deploy
        # canary); fails typed instead of failing over
        self.pinned = pinned
        self.owner: Optional["_GenReplicaClient"] = None


# replica client states (same lifecycle as fleet.py's _ReplicaClient)
_STARTING = "starting"
_STANDBY = "standby"
_READY = "ready"
_DRAINING = "draining"
_FAILED = "failed"
_RETIRED = "retired"


class _GenReplicaClient:
    """Fleet-side handle to one generation replica subprocess: the
    connection, the live-stream ledger, the breaker, the puller, and
    the receiver routing token/stream_end frames. The extra state over
    the inference fleet's client is the stream plane: ``streams`` maps
    stream id -> request for exactly-once routing, ``last_frame``
    feeds the wedged-stream detector, and the pool stats piggybacked
    on every pong feed KV-pressure-aware pulling."""

    def __init__(self, fleet: "GenerationFleet", rank: int,
                 version: str, endpoint_path: str,
                 probation: bool = False):
        self.fleet = fleet
        self.rank = rank
        self.version = version
        self.endpoint_path = endpoint_path
        self.expected_incarnation = 0
        self.probation = probation
        self.state = _STARTING
        # deliberate hold-across-sendall: serializes frames on the one
        # socket (see fleet.py)
        self.send_lock = threading.Lock()
        self.lock = locks.make_lock(f"GenReplicaClient[{rank}].lock")
        self.cond = threading.Condition(self.lock)
        self.conn: Optional[socket.socket] = None   # guarded-by: self.lock
        self.streams: Dict[int, _GenStreamReq] = {}  # guarded-by: self.lock
        self.last_frame = time.monotonic()          # guarded-by: self.lock
        self.consecutive_failures = 0               # guarded-by: self.lock
        self.needs_restart = False                  # guarded-by: self.lock
        self._recv_gen = 0                          # guarded-by: self.lock
        # latest pong intel (handshake + periodic sweep pings)
        self.slots = 0
        self.pool: Optional[dict] = None
        self.decode_compiles = 0
        self.parked = 0
        self.puller = threading.Thread(
            target=self._puller_loop, daemon=True,
            name=f"p1t-genfleet-pull-{rank}")

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self.puller.start()

    def set_state(self, state: str) -> None:
        with self.cond:
            self.state = state
            self.cond.notify_all()

    def wait_connected(self, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        with self.cond:
            while self.state not in (_STANDBY, _READY):
                if self.state in (_FAILED, _RETIRED):
                    return False
                rem = deadline - time.monotonic()
                if rem <= 0:
                    return False
                self.cond.wait(min(rem, 0.1))
            return True

    def enter_rotation(self) -> None:
        self.probation = False
        self.set_state(_READY)
        self.fleet._notify_queue()

    def stream_slots(self) -> int:
        """Concurrent streams this replica should hold: the
        ``serve_gen_streams_per_replica`` flag, or (when 0) the
        replica's own decode slot count from its pong."""
        cap = self.fleet.streams_per_replica
        return cap if cap > 0 else max(1, self.slots)

    # -- connect / handshake -----------------------------------------------

    def _adopt_pong(self, header: dict) -> None:
        self.slots = int(header.get("slots", self.slots) or 0)
        self.decode_compiles = int(header.get("decode_compiles", 0))
        self.parked = int(header.get("parked", 0))
        pool = header.get("pool")
        if pool is not None:
            self.pool = dict(pool)
        v = header.get("version")
        if v:
            self.version = v

    def _try_connect(self) -> bool:
        try:
            with open(self.endpoint_path) as f:
                ep = json.load(f)
        except (OSError, ValueError):
            return False
        if int(ep.get("incarnation", -1)) != self.expected_incarnation:
            return False  # stale endpoint from a previous life
        try:
            conn = socket.create_connection(
                ("127.0.0.1", int(ep["port"])), timeout=2.0)
        except OSError:
            return False
        try:
            conn.settimeout(5.0)
            wire.send_msg(conn, {"kind": "ping", "id": -1})
            header, _ = wire.recv_msg(conn)
            if header.get("kind") != "pong":
                conn.close()
                return False
            self._adopt_pong(header)
        except (OSError, ConnectionError, ValueError):
            try:
                conn.close()
            except OSError:
                pass
            return False
        conn.settimeout(0.25)
        with self.lock:
            self.conn = conn
            self.consecutive_failures = 0
            self.last_frame = time.monotonic()
            self._recv_gen += 1
            gen = self._recv_gen
        threading.Thread(target=self._receiver_loop, args=(conn, gen),
                         daemon=True,
                         name=f"p1t-genfleet-recv-{self.rank}").start()
        self.set_state(_STANDBY if self.probation else _READY)
        self.fleet._notify_queue()
        return True

    # -- puller -------------------------------------------------------------

    def _puller_loop(self) -> None:
        fleet = self.fleet
        while not fleet._stop:
            state = self.state
            if state == _STARTING:
                if not self._try_connect():
                    time.sleep(0.05)
                continue
            if state in (_FAILED, _RETIRED):
                return
            if state != _READY or self.conn is None:
                time.sleep(0.02)
                continue
            with self.cond:
                if len(self.streams) >= self.stream_slots():
                    # stream window full: wait for an end/loss to open
                    # a slot (stream_end/transport-loss notify)
                    self.cond.wait(0.05)
                    continue
            req = fleet._next_stream(self)
            if req is None:
                continue
            self._dispatch(req)

    def _page_headroom_ok(self, req: _GenStreamReq) -> bool:
        """KV-pressure gate: don't place a stream whose worst-case page
        footprint exceeds this replica's last-reported free pages. Only
        advisory — stale by one pong, and relaxed by the caller once
        the queue head ages (replica-side preemption is the real
        backstop)."""
        pool = self.pool
        if not pool:
            return True  # unpaged replica (or no intel yet)
        ps = int(pool.get("page_size", 0))
        if ps <= 0:
            return True
        done = req.stream._count()
        need = -(-(int(req.prompt.size) + done + req.max_new
                   - done) // ps)  # ceil((prompt + max_new)/page_size)
        free = int(pool.get("pages_free", 0)) \
            + int(pool.get("pages_cached", 0))  # cached pages evict
        return need <= free

    def _dispatch(self, req: _GenStreamReq) -> None:
        fleet = self.fleet
        conn = self.conn
        if conn is None:
            if req.pinned:
                fleet._fail_stream(req, StreamFailed(
                    f"pinned stream's replica {self.rank} connection "
                    "lost before dispatch"))
                return
            # never reached a replica: front of the queue, no retry
            with fleet._queue_cond:
                fleet._queue.appendleft(req)
                fleet._queue_cond.notify()
            return
        now = time.monotonic()
        remaining_ms = None
        if req.deadline is not None:
            remaining_ms = (req.deadline - now) * 1e3
            if remaining_ms <= 0.0:
                fleet._resolve_deadline(req, "expired before dispatch")
                return
        toks = req.stream.tokens  # replay snapshot (only receivers
        # append, and this stream is registered on no replica right now)
        resume_n = len(toks)
        full = np.concatenate(
            [req.prompt, np.asarray(toks, np.int64)]) if resume_n \
            else req.prompt
        with self.cond:
            self.streams[req.id] = req
            req.owner = self
            self.last_frame = now  # a fresh stream isn't "silent" yet
        header = {"kind": "generate", "id": req.id, "seed": req.seed,
                  "max_new": req.max_new,
                  "temperature": req.temperature, "top_k": req.top_k,
                  "deadline_ms": remaining_ms,
                  "priority": req.priority, "resume": resume_n}
        try:
            with self.send_lock:
                wire.send_msg(conn, header, [full])  # noqa: lock-blocking — lock is FOR sendall
        except (OSError, ConnectionError):
            self._on_transport_loss("send failed")

    # -- receiver -----------------------------------------------------------

    def _receiver_loop(self, conn: socket.socket, gen: int) -> None:
        fleet = self.fleet

        def idle():
            if fleet._stop or self._recv_gen != gen:
                raise ConnectionError("receiver superseded")

        while True:
            try:
                header, _ = wire.recv_msg(conn, idle=idle)
            except (ConnectionError, OSError):
                if self._recv_gen == gen and not fleet._stop:
                    self._on_transport_loss("connection lost")
                return
            kind = header.get("kind")
            if kind == wire.STREAM_TOKENS:
                self._on_tokens(header)
            elif kind == wire.STREAM_END:
                self._on_stream_end(header)
            elif kind in ("pong", "metrics_result"):
                if kind == "pong":
                    self._adopt_pong(header)
                fleet._resolve_rpc(self, header)

    def _pop_stream(self, rid) -> Optional[_GenStreamReq]:
        with self.cond:
            req = self.streams.pop(rid, None)
            if req is not None:
                if req.owner is self:
                    req.owner = None
                self.cond.notify()  # a stream slot opened
        return req

    def _on_tokens(self, header: dict) -> None:
        fleet = self.fleet
        with self.cond:
            req = self.streams.get(header.get("id"))
            self.last_frame = time.monotonic()
        if req is None:
            return  # late frame from a migrated/failed-over stream
        status = req.stream._feed(int(header.get("seq", 0)),
                                  header.get("toks") or [])
        if status == "ok":
            fleet.metrics.counter("gen_fleet_tokens_total").inc(
                len(header.get("toks") or []))
        elif status == "dup":
            fleet.metrics.counter("gen_fleet_dup_tokens_total").inc()
        else:  # gap: the replica's stream plane is desynced — the
            # exactly-once contract says fail over, never deliver
            self._pop_stream(req.id)
            fleet.metrics.counter("gen_fleet_failovers_total").inc()
            fleet._failover(req, f"replica {self.rank} sent seq "
                                 f"{header.get('seq')} past the "
                                 "stream's next index (desynced)")

    def _on_stream_end(self, header: dict) -> None:
        fleet = self.fleet
        req = self._pop_stream(header.get("id"))
        with self.lock:
            self.last_frame = time.monotonic()
        if req is None:
            return  # migrated away; the old replica's epilogue
        reason = str(header.get("reason", "error"))
        etype = header.get("etype")
        msg = str(header.get("msg", ""))
        n = int(header.get("count", 0))
        if reason in ("eos", "length"):
            if n != req.stream._count():
                # the replica thinks it sent n tokens; we hold fewer —
                # frames were lost to a race. Replay fills the hole.
                fleet.metrics.counter("gen_fleet_failovers_total").inc()
                fleet._failover(
                    req, f"replica {self.rank} closed the stream at "
                         f"{n} tokens but {req.stream._count()} "
                         "arrived")
                return
            with self.lock:
                self.consecutive_failures = 0
            fleet._resolve_done(req, reason)
            return
        if reason == "cancelled":
            fleet._resolve_cancelled(req)
            return
        if reason in ("deadline", "budget"):
            fleet._resolve_error(req, reason, DeadlineExceeded(
                msg or f"stream deadline expired on replica "
                       f"{self.rank}"))
            return
        # reason == "error" (or unknown): route by etype
        if etype in _FAILOVER_ETYPES:
            fleet.metrics.counter("gen_fleet_failovers_total").inc()
            fleet._failover(
                req, f"replica {self.rank} refused/faulted: "
                     f"{etype}: {msg}")
            return
        if etype == "DeadlineExceeded":
            fleet._resolve_error(req, "deadline", DeadlineExceeded(msg))
            return
        if etype == "InvalidArgumentError":
            fleet._resolve_error(req, "error", InvalidArgumentError(msg))
            return
        if etype == "StreamCancelled":
            fleet._resolve_cancelled(req)
            return
        # unknown error: evidence the replica is broken — breaker, and
        # the stream still fails over (replay elsewhere is safe: tokens
        # already delivered are immutable, the continuation replays)
        with self.lock:
            self.consecutive_failures += 1
            if self.consecutive_failures >= fleet.breaker_failures:
                self.needs_restart = True
        fleet.metrics.counter("gen_fleet_failovers_total").inc()
        fleet._failover(req, f"replica {self.rank} stream error "
                             f"[{etype}]: {msg}")

    # -- failure handling ---------------------------------------------------

    def _on_transport_loss(self, reason: str) -> None:
        """The replica died or its connection broke: fail over every
        live stream (replay from what the client already holds) and go
        back to connecting."""
        with self.cond:
            conn, self.conn = self.conn, None
            self._recv_gen += 1
            lost = list(self.streams.values())
            self.streams.clear()
            for req in lost:
                if req.owner is self:
                    req.owner = None
            self.cond.notify_all()
            if conn is not None:
                # close INSIDE the lock: a puller that captured this
                # conn must get a deterministic send error (fleet.py's
                # stranded-inflight race, same fix)
                try:
                    conn.close()
                except OSError:
                    pass
        if self.state in (_READY, _STANDBY, _STARTING):
            self.set_state(_STARTING)
        if lost:
            self.fleet.metrics.counter(
                "gen_fleet_failovers_total").inc(len(lost))
        for req in lost:
            self.fleet._failover(req, f"replica {self.rank} {reason}")

    def sweep_wedged(self, now: float, timeout_s: float) -> bool:
        """Wedged-stream transport deadline: live streams but no frame
        (token, end, or pong) for ``timeout_s`` — the replica's
        heartbeat may still beat, but its token plane is dead. Fail
        everything over and ask for a restart."""
        with self.lock:
            wedged = bool(self.streams) and \
                (now - self.last_frame) > timeout_s
            if wedged:
                self.needs_restart = True
        if not wedged:
            return False
        self._on_transport_loss(
            f"wedged: live streams silent > {timeout_s:.1f}s")
        return True

    def on_process_restart(self, new_incarnation: int) -> None:
        with self.lock:
            self.expected_incarnation = int(new_incarnation)
            self.needs_restart = False
        self._on_transport_loss("restarted by supervisor")
        if self.state not in (_FAILED, _RETIRED):
            self.set_state(_STARTING)

    def mark_failed(self) -> None:
        self.set_state(_FAILED)  # terminal first (loss can't reset it)
        self._on_transport_loss("restart budget exhausted")


class GenerationFleet:
    """Multi-replica HA front end over
    :class:`~paddle1_tpu.serving.GenerationServer` workers (module
    docstring). ``model`` is a replica model spec —
    ``'file.py:factory'``, ``'module:factory'`` (called with
    ``model_arg``), or ``'artifact:/path'``. Engine/server keyword
    arguments (``slots``, ``max_seq``, ``paged``, ``pages``,
    ``spec_tokens``, ``preempt``, ...) are forwarded to every replica
    via ``--gen-config``."""

    def __init__(self, model: str, replicas: Optional[int] = None,
                 version: str = "v1", model_arg: str = "",
                 retry_max: Optional[int] = None,
                 stream_timeout_ms: Optional[float] = None,
                 streams_per_replica: Optional[int] = None,
                 breaker_failures: Optional[int] = None,
                 queue_depth: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 ready_timeout_s: Optional[float] = None,
                 hang_timeout: Optional[float] = None,
                 max_restarts: Optional[int] = None,
                 env: Optional[dict] = None,
                 work_dir: Optional[str] = None,
                 chaos_spec: Optional[str] = None,
                 poll_s: float = 0.2,
                 **gen_config):
        self.model_spec = str(model)
        self.model_arg = str(model_arg)
        self.version = str(version)
        self.replica_count = int(
            core_flags.flag("serve_gen_replicas") if replicas is None
            else replicas)
        if self.replica_count < 1:
            raise InvalidArgumentError("a fleet needs >= 1 replica")
        self.retry_max = int(
            core_flags.flag("serve_retry_max") if retry_max is None
            else retry_max)
        self.stream_timeout_s = float(
            core_flags.flag("serve_gen_stream_timeout_ms")
            if stream_timeout_ms is None else stream_timeout_ms) / 1e3
        self.streams_per_replica = int(
            core_flags.flag("serve_gen_streams_per_replica")
            if streams_per_replica is None else streams_per_replica)
        self.breaker_failures = int(
            core_flags.flag("serve_breaker_failures")
            if breaker_failures is None else breaker_failures)
        self.queue_depth = int(
            core_flags.flag("serve_fleet_queue_depth")
            if queue_depth is None else queue_depth)
        self.ready_timeout_s = float(
            core_flags.flag("serve_ready_timeout_s")
            if ready_timeout_s is None else ready_timeout_s)
        dl = deadline_ms if deadline_ms is not None \
            else core_flags.flag("serve_deadline_ms")
        self.default_deadline_ms = float(dl) if dl else None
        self.poll_s = float(poll_s)
        self.hang_timeout = hang_timeout
        self.max_restarts = max_restarts
        self._user_env = dict(env) if env else {}
        self._work_dir = work_dir
        self._chaos_spec = (core_chaos.active_spec()
                            if chaos_spec is None else chaos_spec)
        self._gen_config = {k: v for k, v in gen_config.items()
                            if v is not None}

        self.metrics = ServingMetrics()
        self._lock = locks.make_lock("GenerationFleet._lock")
        self._queue_cond = threading.Condition(self._lock)
        self._deploy_lock = locks.make_lock(
            "GenerationFleet._deploy_lock", allow_blocking=True)
        self.healthy = True                  # guarded-by: self._lock
        self._sup = None
        self._clients: Dict[int, _GenReplicaClient] = {}  # guarded-by: self._lock
        self._next_rank = 0                  # guarded-by: self._lock
        self._rid = 0                        # guarded-by: self._lock
        self._seed_counter = 0               # guarded-by: self._lock
        self._queue = collections.deque()    # guarded-by: self._lock
        self._live: Dict[int, _GenStreamReq] = {}       # guarded-by: self._lock
        self._rpc_waiters: Dict[int, dict] = {}         # guarded-by: self._lock
        self._accepting = False              # guarded-by: self._lock
        self._stop = False
        self._started = False
        self._drained = False
        self._sweeper: Optional[threading.Thread] = None
        self._last_ping = 0.0
        self.deploys = 0                     # guarded-by: self._deploy_lock
        self.migrations = 0                  # guarded-by: self._deploy_lock

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "GenerationFleet":
        if self._started:
            return self
        from ..distributed.supervisor import Supervisor
        core_health.beat()
        if self._work_dir is None:
            self._work_dir = tempfile.mkdtemp(prefix="p1t_genfleet_")
        os.makedirs(self._work_dir, exist_ok=True)
        kw = {}
        if self.hang_timeout is not None:
            kw["hang_timeout"] = self.hang_timeout
        if self.max_restarts is not None:
            kw["max_restarts"] = self.max_restarts
        self._sup = Supervisor(policy="restart", elastic=False,
                               heartbeat_dir=os.path.join(
                                   self._work_dir, "hb"),
                               log_dir=self._work_dir,
                               poll_s=min(self.poll_s, 0.5),
                               grace_s=10.0, **kw)
        for _ in range(self.replica_count):
            self._add_replica(self.version, self.model_arg)
        self._sup.start()
        for c in self._clients.values():
            c.start()
        with self._lock:
            self._accepting = True
        self._started = True
        self._sweeper = threading.Thread(target=self._sweep_loop,
                                         daemon=True,
                                         name="p1t-genfleet-sweep")
        self._sweeper.start()
        return self

    def __enter__(self) -> "GenerationFleet":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.drain()
        return False

    def _replica_cmd(self, rank: int, version: str,
                     model_arg: str) -> List[str]:
        ep = os.path.join(self._work_dir, f"genreplica.{rank}.json")
        cmd = [sys.executable, "-u", "-m",
               "paddle1_tpu.serving.genreplica",
               "--endpoint-file", ep, "--model", self.model_spec,
               "--model-arg", model_arg, "--version", version,
               "--rank", str(rank),
               "--gen-config", json.dumps(self._gen_config)]
        if self._chaos_spec:
            cmd += ["--chaos", self._chaos_spec]
        return cmd

    def _replica_env(self) -> dict:
        env = {k: v for k, v in os.environ.items()
               if not k.startswith("PADDLE_FT_")}
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        pp = env.get("PYTHONPATH", "")
        if pkg_root not in pp.split(os.pathsep):
            env["PYTHONPATH"] = (pkg_root + (os.pathsep + pp if pp
                                             else ""))
        env.update(self._user_env)
        return env

    def _add_replica(self, version: str, model_arg: str,
                     probation: bool = False,
                     max_restarts: Optional[int] = None
                     ) -> _GenReplicaClient:
        with self._lock:
            rank = self._next_rank
            self._next_rank += 1
        ep = os.path.join(self._work_dir, f"genreplica.{rank}.json")
        try:  # a stale endpoint from a previous rank must never match
            os.unlink(ep)
        except OSError:
            pass
        self._sup.add_worker(
            rank, self._replica_cmd(rank, version, model_arg),
            env=self._replica_env(),
            log_path=os.path.join(self._work_dir,
                                  f"genreplica.{rank}.log"),
            role="genreplica", max_restarts=max_restarts)
        client = _GenReplicaClient(self, rank, version, ep,
                                   probation=probation)
        with self._lock:
            self._clients[rank] = client
        return client

    # -- request path -------------------------------------------------------

    def submit(self, prompt_ids: Sequence[int],
               max_new_tokens: Optional[int] = None,
               temperature: float = 0.0, top_k: int = 0,
               seed: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               priority: int = 0) -> FleetStream:
        """Open one token stream; returns its :class:`FleetStream`.
        Sheds with :class:`ServerOverloaded` (bounded queue) or raises
        :class:`ServerClosed` synchronously. The seed is minted
        fleet-side when absent — failover replay needs the SAME seed to
        be bit-identical, so the fleet, not the replica, owns it.
        ``priority`` (0 = highest) rides the wire into replica-side
        KV-pressure preemption."""
        if not self._accepting:
            raise ServerClosed(
                "generation fleet is draining/stopped — not admitting")
        prompt = np.asarray(
            getattr(prompt_ids, "numpy", lambda: prompt_ids)(),
            ).astype(np.int64).reshape(-1)
        if prompt.size < 1:
            raise InvalidArgumentError("submit needs >= 1 prompt token")
        if max_new_tokens is not None and int(max_new_tokens) < 1:
            raise InvalidArgumentError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        dl = deadline_ms if deadline_ms is not None \
            else self.default_deadline_ms
        with self._queue_cond:
            if not self._accepting:
                raise ServerClosed(
                    "generation fleet is draining/stopped — not "
                    "admitting")
            self.metrics.counter("gen_fleet_streams_total").inc()
            if len(self._queue) >= self.queue_depth:
                self.metrics.counter("gen_fleet_shed_total").inc()
                raise ServerOverloaded(
                    f"fleet queue depth {self.queue_depth} exhausted — "
                    "stream shed (add replicas, raise "
                    "serve_fleet_queue_depth, or slow the client)")
            self._rid += 1
            if seed is None:
                self._seed_counter += 1
                seed = self._seed_counter
            req = _GenStreamReq(
                self._rid, prompt.astype(np.int64),
                int(max_new_tokens) if max_new_tokens is not None
                else int(core_flags.flag("serve_gen_token_budget")),
                temperature, top_k, int(seed),
                dl / 1e3 if dl else None, dl, priority)
            self._live[req.id] = req
            self._queue.append(req)
            self.metrics.gauge("gen_fleet_streams_active").set(
                len(self._live))
            self._queue_cond.notify()
        req.stream._cancel_cb = lambda _s, r=req: self._cancel(r)
        return req.stream

    def generate(self, prompt_ids, timeout: Optional[float] = None,
                 **kw) -> List[int]:
        """Synchronous convenience: ``submit(...).result(timeout)``."""
        return self.submit(prompt_ids, **kw).result(timeout)

    def _notify_queue(self) -> None:
        with self._queue_cond:
            self._queue_cond.notify_all()

    def _next_stream(self, client: _GenReplicaClient
                     ) -> Optional[_GenStreamReq]:
        """Pop the next dispatchable stream for ``client`` (pullers
        call this). Applies the KV-pressure gate: a stream that won't
        fit the replica's reported free pages stays queued — unless it
        has aged past half a second (head-of-line starvation beats an
        advisory gate; the replica's preemption/parking is the real
        backstop)."""
        with self._queue_cond:
            if not self._queue:
                self._queue_cond.wait(0.05)
            if not self._queue:
                return None
            head = self._queue[0]
            if not client._page_headroom_ok(head) and \
                    time.monotonic() - head.t_enq < 0.5:
                self.metrics.counter(
                    "gen_fleet_pressure_deferrals_total").inc()
                return None
            req = self._queue.popleft()
        if req.stream.done():  # failed/cancelled while queued
            return None
        if req.stream._cancel_requested:
            self._resolve_cancelled(req)
            return None
        if req.deadline is not None and time.monotonic() > req.deadline:
            self._resolve_deadline(req, "expired in the fleet queue")
            return None
        return req

    # -- cancel -------------------------------------------------------------

    def _cancel(self, req: _GenStreamReq) -> None:
        """FleetStream.cancel() hook: tell the owning replica (it ends
        the stream ``cancelled`` through the normal epilogue), or — if
        the stream is still queued / orphaned — resolve it locally."""
        owner = req.owner
        if owner is not None:
            conn = owner.conn
            if conn is not None:
                try:
                    frame = {"kind": "cancel", "stream": req.id}
                    with owner.send_lock:
                        wire.send_msg(conn, frame)  # noqa: lock-blocking — lock is FOR sendall
                    return  # replica's stream_end resolves it
                except (OSError, ConnectionError):
                    pass  # fall through: resolve locally
            owner._pop_stream(req.id)
        self._resolve_cancelled(req)

    # -- resolution / failover ----------------------------------------------

    def _unlive(self, req: _GenStreamReq) -> None:
        with self._lock:
            self._live.pop(req.id, None)
            self.metrics.gauge("gen_fleet_streams_active").set(
                len(self._live))

    def _resolve_done(self, req: _GenStreamReq, reason: str) -> None:
        if req.stream._finish(reason):
            self._unlive(req)
            self.metrics.counter(
                "gen_fleet_streams_completed_total").inc()
            self.metrics.histogram("gen_fleet_stream_ms").observe(
                (time.monotonic() - req.t_enq) * 1e3)
            self.metrics.record_response()

    def _resolve_cancelled(self, req: _GenStreamReq) -> None:
        if req.stream._finish("cancelled", StreamCancelled(
                "stream cancelled by the client — tokens already "
                "delivered stay valid")):
            self._unlive(req)
            self.metrics.counter("gen_fleet_cancelled_total").inc()

    def _resolve_deadline(self, req: _GenStreamReq, where: str) -> None:
        if req.stream._finish("deadline", DeadlineExceeded(
                f"stream {where} after "
                f"{(time.monotonic() - req.t_enq) * 1e3:.1f}ms "
                f"(deadline {req.deadline_ms}ms)")):
            self._unlive(req)
            self.metrics.counter("gen_fleet_deadline_expired_total").inc()

    def _resolve_error(self, req: _GenStreamReq, reason: str,
                       exc: BaseException) -> None:
        if req.stream._finish(reason, exc):
            self._unlive(req)
            if isinstance(exc, DeadlineExceeded):
                self.metrics.counter(
                    "gen_fleet_deadline_expired_total").inc()
            else:
                self.metrics.counter("gen_fleet_errors_total").inc()

    def _fail_stream(self, req: _GenStreamReq,
                     exc: BaseException) -> None:
        if req.stream._finish("failed", exc):
            self._unlive(req)
            self.metrics.counter("gen_fleet_errors_total").inc()
            self.metrics.counter("gen_fleet_stream_failed_total").inc()

    def _failover(self, req: _GenStreamReq, reason: str,
                  charge_retry: bool = True) -> None:
        """Re-admit a stream from ``prompt + tokens already received``
        on a survivor (the replay is bit-identical: same seed, next
        token index). ``charge_retry=False`` is the migration path — a
        deploy moving streams off a retiring replica is policy, not
        failure."""
        if req.stream.done():
            self._unlive(req)
            return
        if req.stream._cancel_requested:
            self._resolve_cancelled(req)
            return
        if req.stream._count() >= req.max_new:
            # the replica died between its last token frame and the
            # stream_end: the client already holds every token the
            # uninterrupted run would produce — complete, don't replay
            self._resolve_done(req, "length")
            return
        if req.pinned:
            self._fail_stream(req, StreamFailed(
                f"pinned stream's replica failed: {reason}"))
            return
        if req.deadline is not None and \
                time.monotonic() > req.deadline:
            self._resolve_deadline(req, f"expired during failover "
                                        f"({reason})")
            return
        if charge_retry:
            req.retries += 1
            if req.retries > self.retry_max:
                self._fail_stream(req, StreamFailed(
                    f"stream failed over {req.retries - 1} times "
                    f"(serve_retry_max={self.retry_max}); last: "
                    f"{reason}"))
                return
            self.metrics.counter("gen_fleet_retries_total").inc()
        obs_events.emit("gen_stream_failover", stream=req.id,
                        tokens=req.stream._count(),
                        retries=req.retries,
                        migration=not charge_retry, reason=reason)
        with self._queue_cond:
            self._queue.appendleft(req)
            self._queue_cond.notify()

    # -- supervision sweep --------------------------------------------------

    def _sweep_loop(self) -> None:
        while not self._stop:
            try:
                self._sweep_once()
            except Exception as e:  # noqa: broad-except — supervision
                # must survive transient teardown races
                print(f"genfleet sweep error: {e!r}", file=sys.stderr)
            time.sleep(self.poll_s)

    def _sweep_once(self) -> None:
        core_health.beat()
        if core_health.drain_requested() and self._accepting:
            self.drain()
            return
        now = time.monotonic()
        for ev in self._sup.supervise_once():
            client = self._clients.get(ev.rank)
            if client is None:
                continue
            if ev.action == "restarted":
                self.metrics.counter(
                    "gen_fleet_replica_restarts_total").inc()
                try:
                    inc = self._sup.incarnation(ev.rank)
                except InvalidArgumentError:
                    continue  # retired by a concurrent deploy
                client.on_process_restart(inc)
            elif ev.action == "restart_exhausted":
                self._on_replica_exhausted(client, ev)
        with self._lock:
            clients = list(self._clients.values())
        for client in clients:
            if client.state in (_FAILED, _RETIRED, _DRAINING):
                continue
            if client.sweep_wedged(now, self.stream_timeout_s):
                self.metrics.counter(
                    "gen_fleet_replica_wedged_total").inc()
            with client.lock:  # atomic test-and-clear (fleet.py race)
                needs_restart = client.needs_restart
                client.needs_restart = False
            if needs_restart:
                if client.state not in (_FAILED, _RETIRED, _DRAINING):
                    try:
                        restarted = self._sup.restart_rank(client.rank)
                        inc = (self._sup.incarnation(client.rank)
                               if restarted else 0)
                    except InvalidArgumentError:
                        continue
                    if restarted:
                        self.metrics.counter(
                            "gen_fleet_replica_restarts_total").inc()
                        client.on_process_restart(inc)
                    else:
                        self._on_replica_exhausted(client, None)
        # periodic pong refresh: KV-pressure intel + ready gauges (a
        # fire-and-forget frame — the receiver adopts the pong, so the
        # sweep never blocks on a replica)
        if now - self._last_ping >= 1.0:
            self._last_ping = now
            ready = 0
            pages_free = 0
            any_pool = False
            for client in clients:
                if client.state == _READY:
                    ready += 1
                    conn = client.conn
                    if conn is not None:
                        try:
                            frame = {"kind": "ping", "id": -2}
                            with client.send_lock:
                                wire.send_msg(conn, frame)  # noqa: lock-blocking — sendall lock
                        except (OSError, ConnectionError):
                            pass
                    if client.pool:
                        any_pool = True
                        pages_free += int(
                            client.pool.get("pages_free", 0))
            self.metrics.gauge("gen_fleet_replicas_ready").set(ready)
            if any_pool:
                self.metrics.gauge("gen_fleet_kv_pages_free").set(
                    pages_free)
        # queued streams whose deadline passed while nobody pulled
        expired = []
        with self._queue_cond:
            if self._queue:
                keep = collections.deque()
                for req in self._queue:
                    if req.deadline is not None and now > req.deadline:
                        expired.append(req)
                    else:
                        keep.append(req)
                self._queue = keep
        for req in expired:
            self._resolve_deadline(req, "expired in the fleet queue")
        if not any(c.state in (_STARTING, _STANDBY, _READY, _DRAINING)
                   for c in clients):
            self._fail_all_pending(StreamFailed(
                "no generation replicas left in the fleet (restart "
                "budgets exhausted)"))

    def _on_replica_exhausted(self, client: _GenReplicaClient,
                              ev) -> None:
        client.mark_failed()
        if self._sup is not None:
            self._sup.kill_worker(client.rank)
        if client.probation:
            return  # a dying deploy candidate is the deploy's failure
        with self._lock:
            self.healthy = False
        self.metrics.counter("gen_fleet_replica_exhausted_total").inc()
        reason = (f"generation fleet: replica {client.rank} out of "
                  f"restart budget"
                  + (f" ({ev.failure.kind}: {ev.failure.reason})"
                     if ev is not None else ""))
        print(reason, file=sys.stderr)
        core_health.report_unhealthy(reason)

    def _fail_all_pending(self, exc: BaseException) -> None:
        with self._queue_cond:
            pending = list(self._queue)
            self._queue.clear()
            live = list(self._live.values())
        for req in pending + live:
            self._fail_stream(req, exc)

    # -- replica RPC --------------------------------------------------------

    def _rpc(self, client: _GenReplicaClient, kind: str,
             timeout: float = 10.0) -> Optional[dict]:
        conn = client.conn
        if conn is None:
            return None
        with self._lock:
            self._rid += 1
            rid = self._rid
            waiter = {"event": threading.Event(), "header": None}
            self._rpc_waiters[rid] = waiter
        try:
            with client.send_lock:
                wire.send_msg(conn, {"kind": kind, "id": rid})  # noqa: lock-blocking — send lock
        except (OSError, ConnectionError):
            with self._lock:
                self._rpc_waiters.pop(rid, None)
            return None
        if not waiter["event"].wait(timeout):
            with self._lock:
                self._rpc_waiters.pop(rid, None)
            return None
        return waiter["header"]

    def _resolve_rpc(self, client: _GenReplicaClient, header) -> None:
        with self._lock:
            waiter = self._rpc_waiters.pop(header.get("id"), None)
        if waiter is not None:
            waiter["header"] = header
            waiter["event"].set()

    def replica_snapshot(self, rank: int,
                         timeout: float = 10.0) -> Optional[dict]:
        """One replica's own ServingMetrics snapshot, over the wire."""
        client = self._clients.get(rank)
        if client is None:
            return None
        header = self._rpc(client, "metrics", timeout)
        return header.get("snapshot") if header else None

    # -- hot swap -----------------------------------------------------------

    def deploy(self, model: str, version: str, model_arg: str = "",
               canary_prompt: Optional[Sequence[int]] = None,
               ready_timeout_s: Optional[float] = None) -> dict:
        """Zero-downtime rolling model swap. The first new replica is
        the canary (zero restart budget; ``canary_prompt``, when given,
        must stream to completion ON the candidate — pinned, it never
        fails over to the standing fleet). Each retiring replica's live
        streams are MIGRATED by replay onto the survivors — same
        mechanism as failover, no retry budget charged — so a deploy
        never kills a stream. Raises :class:`DeployFailed` with the old
        fleet intact when the canary fails; later failures roll the
        already-promoted slots back."""
        timeout = (self.ready_timeout_s if ready_timeout_s is None
                   else float(ready_timeout_s))
        with self._deploy_lock:
            if not self._started or self._stop:
                raise PreconditionNotMetError(
                    "fleet is not running — nothing to deploy onto")
            old_spec, old_arg, old_version = (
                self.model_spec, self.model_arg, self.version)
            with self._lock:
                old_ranks = [r for r, c in self._clients.items()
                             if c.state in (_STARTING, _READY)]
            if not old_ranks:
                raise PreconditionNotMetError(
                    "no serving replicas to roll")
            self.model_spec = str(model)
            self.model_arg = str(model_arg)
            swapped: List[int] = []
            try:
                for i, old_rank in enumerate(sorted(old_ranks)):
                    new = self._swap_in(version, model_arg,
                                        canary_prompt, timeout,
                                        canary_slot=(i == 0))
                    self._retire_replica(old_rank)
                    swapped.append(new.rank)
            except DeployFailed:
                self.metrics.counter("gen_fleet_rollbacks_total").inc()
                obs_events.emit("gen_deploy_rollback",
                                version=str(version),
                                promoted=len(swapped))
                self.model_spec, self.model_arg = old_spec, old_arg
                for new_rank in swapped:
                    try:
                        self._swap_in(old_version, old_arg, None,
                                      timeout, canary_slot=False)
                        self._retire_replica(new_rank)
                    except DeployFailed:  # pragma: no cover -
                        break  # survivors keep serving
                raise
            self.version = str(version)
            self.deploys += 1
            self.metrics.counter("gen_fleet_deploys_total").inc()
            obs_events.emit("gen_deploy", version=str(version),
                            replicas=list(swapped))
            return {"version": version, "replicas": swapped,
                    "rolled": len(swapped)}

    def _swap_in(self, version: str, model_arg: str, canary_prompt,
                 timeout: float,
                 canary_slot: bool) -> _GenReplicaClient:
        client = self._add_replica(version, model_arg, probation=True,
                                   max_restarts=0 if canary_slot
                                   else None)
        self._sup.spawn_worker(client.rank)
        client.start()
        ok = client.wait_connected(timeout)
        if ok and canary_prompt is not None:
            ok = self._canary_generate(client, canary_prompt, timeout)
        if not ok:
            self._abort_spawn(client)
            raise DeployFailed(
                f"generation replica for version {version!r} never "
                f"became healthy within {timeout:.0f}s"
                + (" (canary)" if canary_slot else "")
                + " — deploy aborted, fleet keeps serving the "
                  "previous version")
        self._sup.set_restart_budget(client.rank, self.max_restarts)
        client.enter_rotation()
        return client

    def _canary_generate(self, client: _GenReplicaClient,
                         canary_prompt, timeout: float) -> bool:
        """One short pinned stream on the off-rotation candidate: it
        must decode to completion on THAT replica (the pin turns any
        failover into a typed failure — a canary answered by the
        standing fleet proves nothing)."""
        prompt = np.asarray(canary_prompt, np.int64).reshape(-1)
        with self._queue_cond:
            self.metrics.counter("gen_fleet_streams_total").inc()
            self._rid += 1
            self._seed_counter += 1
            req = _GenStreamReq(self._rid, prompt, 4, 0.0, 0,
                                self._seed_counter, None, None, 0,
                                pinned=True)
            self._live[req.id] = req
        client._dispatch(req)
        try:
            req.stream.result(timeout=timeout)
        except Exception:  # noqa: broad-except — ANY canary failure
            return False   # means "do not promote"
        # the pin is the proof: tokens route by the candidate's own
        # stream registry, so a completed result came from IT
        return True

    def _abort_spawn(self, client: _GenReplicaClient) -> None:
        client.set_state(_RETIRED)
        client._on_transport_loss("deploy aborted")
        self._sup.retire(client.rank, grace_s=2.0)
        with self._lock:
            self._clients.pop(client.rank, None)

    # -- horizontal scaling (ISSUE 18) --------------------------------------

    def live_replicas(self) -> int:
        """Replicas that count toward capacity: starting, standby, or
        in rotation."""
        with self._lock:
            return sum(1 for c in self._clients.values()
                       if c.state in (_STARTING, _STANDBY, _READY))

    def ready_replicas(self) -> int:
        with self._lock:
            return sum(1 for c in self._clients.values()
                       if c.state == _READY)

    def scale_to(self, replicas: int,
                 ready_timeout_s: Optional[float] = None,
                 reason: str = "requested") -> dict:
        """Zero-downtime horizontal scale to ``replicas`` (the
        :meth:`ServingFleet.scale_to` contract for token streams).
        Scale-in retires the highest ranks through the deploy retire
        path, so their live streams MIGRATE by bit-identical replay to
        survivors rather than failing. Raises :class:`ScaleFailed`
        typed when a scale-out replica never becomes healthy (healthy
        additions stay)."""
        target = int(replicas)
        if target < 1:
            raise InvalidArgumentError(
                f"cannot scale a fleet to {target} replicas")
        with self._deploy_lock:
            if not self._started or self._stop:
                raise ScaleFailed(
                    "generation fleet is not running — nothing to "
                    "scale")
            with self._lock:
                live = sorted(r for r, c in self._clients.items()
                              if c.state in (_STARTING, _STANDBY,
                                             _READY))
            start = len(live)
            if target == start:
                return {"from": start, "to": start, "added": [],
                        "retired": []}
            timeout = (self.ready_timeout_s if ready_timeout_s is None
                       else float(ready_timeout_s))
            added: List[int] = []
            retired: List[int] = []
            if target > start:
                # spawn first, wait second: candidates warm
                # CONCURRENTLY — one spawn latency per transition, not
                # one per added replica (mirrors ServingFleet.scale_to)
                spawned = []
                for _ in range(target - start):
                    client = self._add_replica(self.version,
                                               self.model_arg)
                    self._sup.spawn_worker(client.rank)
                    client.start()
                    spawned.append(client)
                deadline = time.monotonic() + timeout
                failed: List[int] = []
                for client in spawned:
                    if client.wait_connected(
                            max(0.0, deadline - time.monotonic())):
                        added.append(client.rank)
                    else:
                        self._abort_spawn(client)
                        failed.append(client.rank)
                if failed:
                    self._emit_scale(reason, start, added, retired,
                                     refused=True)
                    raise ScaleFailed(
                        f"scale-out replica(s) {failed} never became "
                        f"healthy within {timeout:.0f}s — fleet holds "
                        f"at {start + len(added)} replicas")
            else:
                for rank in reversed(live):
                    if start - len(retired) <= target:
                        break
                    self._retire_replica(rank)
                    retired.append(rank)
            self._emit_scale(reason, start, added, retired)
            return {"from": start, "to": start + len(added)
                    - len(retired), "added": added, "retired": retired}

    def _emit_scale(self, reason: str, start: int, added, retired,
                    refused: bool = False) -> None:
        to = start + len(added) - len(retired)
        self.metrics.counter("scale_out_total" if to >= start
                             else "scale_in_total").inc()
        if refused:
            self.metrics.counter("scale_refused_total").inc()
        obs_events.emit("fleet_scale", kind="generation", reason=reason,
                        replicas_from=start, replicas_to=to,
                        added=list(added), retired=list(retired),
                        refused=bool(refused))

    def _retire_replica(self, rank: int) -> None:
        """Take one replica out of the fleet, migrating its live
        streams by replay (not failover — no retry budget): remove
        each stream from the retiring client FIRST (late frames and
        the cancel-epilogue get dropped by the registry miss), cancel
        it replica-side so the old process stops decoding tokens
        nobody reads, then re-enqueue for a survivor."""
        client = self._clients.get(rank)
        if client is None:
            return
        client.set_state(_DRAINING)
        with client.cond:
            moving = list(client.streams.values())
            client.streams.clear()
            for req in moving:
                if req.owner is client:
                    req.owner = None
            conn = client.conn
        for req in moving:
            if conn is not None:
                try:
                    frame = {"kind": "cancel", "stream": req.id}
                    with client.send_lock:
                        wire.send_msg(conn, frame)  # noqa: lock-blocking — lock is FOR sendall
                except (OSError, ConnectionError):
                    conn = None
            self.metrics.counter("gen_fleet_migrations_total").inc()
            # _retire_replica's only callers (deploy, scale_to) hold
            # _deploy_lock
            self.migrations += 1  # noqa: guarded-mutation — held via deploy()/scale_to()
            self._failover(req, f"migrated off retiring replica "
                                f"{rank}", charge_retry=False)
        client.set_state(_RETIRED)
        self._sup.retire(rank)
        client._on_transport_loss("retired")  # registry already empty
        with self._lock:
            self._clients.pop(rank, None)

    # -- drain --------------------------------------------------------------

    def drain(self, timeout: float = 30.0) -> dict:
        """Stop admitting, let every accepted stream finish (or fail
        typed), scrape each replica's final decode-compile and page
        ledgers, stop the replicas gracefully, report — with the
        accounting identity ``unaccounted == 0``."""
        with self._queue_cond:
            already = self._drained
            self._accepting = False
        per_rank: Dict[int, dict] = {}
        if not already and self._started:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                with self._lock:
                    if not self._live:
                        break
                time.sleep(0.02)
            self._fail_all_pending(PreconditionNotMetError(
                f"generation fleet drain timed out after {timeout}s"))
            # final per-replica ledger scrape BEFORE teardown: the
            # bench's acceptance gates (decode_compile_count == 1 per
            # replica across failovers, kv pages owed) read this
            with self._lock:
                clients = list(self._clients.items())
            for rank, client in clients:
                header = self._rpc(client, "ping", timeout=5.0)
                if header is not None:
                    per_rank[rank] = {
                        "version": header.get("version"),
                        "incarnation": header.get("incarnation"),
                        "decode_compiles":
                            header.get("decode_compiles"),
                        "parked": header.get("parked"),
                        "pool": header.get("pool"),
                    }
        with self._queue_cond:
            self._stop = True
            self._queue_cond.notify_all()
        if self._sup is not None and not already:
            for rank in list(self._clients):
                self._sup.retire(rank, grace_s=10.0)
        self._drained = True
        snap = self.metrics.snapshot()
        c = snap["counters"]
        report = {
            "drained": True,
            "healthy": self.healthy,
            "accepted": (c.get("gen_fleet_streams_total", 0)
                         - c.get("gen_fleet_shed_total", 0)),
            "completed": c.get("gen_fleet_streams_completed_total", 0),
            "deadline_failed":
                c.get("gen_fleet_deadline_expired_total", 0),
            "cancelled": c.get("gen_fleet_cancelled_total", 0),
            "errors": c.get("gen_fleet_errors_total", 0),
            "stream_failed": c.get("gen_fleet_stream_failed_total", 0),
            "shed": c.get("gen_fleet_shed_total", 0),
            "retries": c.get("gen_fleet_retries_total", 0),
            "failovers": c.get("gen_fleet_failovers_total", 0),
            "migrations": c.get("gen_fleet_migrations_total", 0),
            "tokens": c.get("gen_fleet_tokens_total", 0),
            "dup_tokens_dropped":
                c.get("gen_fleet_dup_tokens_total", 0),
            "replica_restarts":
                c.get("gen_fleet_replica_restarts_total", 0),
            "deploys": self.deploys,
            "replicas": per_rank,
            "supervisor": (self._sup.report.as_dict()
                           if self._sup is not None else None),
        }
        report["unaccounted"] = (report["accepted"]
                                 - report["completed"]
                                 - report["deadline_failed"]
                                 - report["cancelled"]
                                 - report["errors"])
        return report

    stop = drain
