"""Shape-bucketed compiled inference: the serving executable cache.

The training-side lessons (PR 1) applied to the serving hot path: every
distinct input signature jitted is a full XLA compile, so an unbucketed
server recompiles on every new micro-batch size and the host loop
serializes behind the compiler. :class:`InferenceEngine` therefore
compiles the forward once per **shape bucket** — a fixed, configurable
list of batch sizes (``serve_buckets`` flag) — and every micro-batch is
padded up to the smallest covering bucket, so steady-state serving runs
a small, warm set of executables (Clipper/NSDI'17 adaptive batching,
compiled-runtime form).

Accounting mirrors ``ParallelEngine``: ``compile_counts``/
``dispatch_counts`` (per bucket) are trace-side-effect counters — the
"exactly one compile per bucket" acceptance gate reads them — and the
warn-once retrace guard reuses the ``jit_retrace_warn`` flag, keyed on
the *inner* signature (dims past the batch axis + dtypes): a new bucket
is an expected, bounded compile; a new inner shape is the unbounded
retrace hazard buckets exist to prevent. The persistent compilation
cache (``jit_cache_dir``) is wired exactly as in training, so serving
workers restarted by the Supervisor skip the recompile storm.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core import flags as core_flags
from ..core import jit_sanitizer
from ..core.errors import InvalidArgumentError, UnimplementedError

__all__ = ["InferenceEngine", "resolve_buckets"]

# numpy's dtype.__str__ walks the dtype registry every call (~10us);
# submit() needs it per request, so cache per dtype object (builtin
# dtypes are singletons)
_DTYPE_STR: Dict[Any, str] = {}


def _dtype_str(dt) -> str:
    s = _DTYPE_STR.get(dt)
    if s is None:
        s = _DTYPE_STR.setdefault(dt, str(dt))
    return s


def resolve_buckets(buckets=None, max_batch: Optional[int] = None,
                    spec_flag: str = "serve_buckets"
                    ) -> Tuple[int, ...]:
    """Normalize the bucket list: explicit sequence > the ``spec_flag``
    flag > powers of two up to ``max_batch`` (``serve_max_batch`` flag).
    Always sorted, deduped, and covering ``max_batch``. The generation
    engine reuses the same policy for PROMPT-LENGTH buckets by passing
    ``spec_flag="serve_gen_prefill_buckets"`` (a different axis, so it
    must never read the batch-size flag)."""
    explicit_max = max_batch is not None
    if max_batch is None:
        max_batch = int(core_flags.flag("serve_max_batch"))
    if buckets is None:
        spec = core_flags.flag(spec_flag)
        if spec:
            try:
                buckets = [int(b) for b in str(spec).split(",") if
                           b.strip()]
            except ValueError:
                raise InvalidArgumentError(
                    f"{spec_flag} must be comma-separated ints, got "
                    f"{spec!r}") from None
    if buckets is None:
        buckets, b = [], 1
        while b < max_batch:
            buckets.append(b)
            b *= 2
        buckets.append(max_batch)
    out = sorted({int(b) for b in buckets})
    if not out or out[0] < 1:
        raise InvalidArgumentError(f"buckets must be >= 1, got {out}")
    if explicit_max and out[-1] < max_batch:
        out.append(max_batch)  # the requested ceiling must dispatch
    return tuple(out)


class InferenceEngine:
    """Compiled eval-mode forward with bucket-padded dispatch.

    Parameters
    ----------
    model : one of
        * ``nn.Layer`` — served via its functional state (params ride as
          jit arguments, not baked constants); forced into eval mode.
        * ``jit.TranslatedLayer`` / ``inference.Predictor`` — the
          deserialized StableHLO artifact is called with its restored
          params threaded through jit.
        * plain callable ``fn(*arrays) -> array(s)`` — already pure.
    buckets : batch-size buckets (see :func:`resolve_buckets`).
    input_specs : optional ``[(shape_without_batch, dtype), ...]`` —
        enables :meth:`warm_up` without example data. Derived from the
        Predictor's ``.pdconfig`` sidecar automatically.
    metrics : optional ServingMetrics to mirror compile counts into.
    """

    def __init__(self, model, buckets=None, max_batch: Optional[int] =
                 None, input_specs=None, metrics=None):
        core_flags.maybe_enable_compilation_cache()
        import jax
        self.metrics = metrics
        self.compile_counts: Dict[int, int] = {}
        self.dispatch_counts: Dict[int, int] = {}
        self._seen_inner_sigs: set = set()
        self._retrace_warned = False
        # None when debug_jit_sanitizer is off (one pointer test per
        # admission). The sanitizer bounds the INNER signature count
        # only: batch-size variation is bucketed by design and all
        # buckets SHARE one inner signature, so the bucket count never
        # approaches the limit — what does is unpadded variable inner
        # shapes, exactly the unbounded hazard buckets can't absorb
        self._jsan = jit_sanitizer.site("InferenceEngine")
        self._lock = threading.Lock()
        self._pure, self._params, specs, fixed_batch = \
            self._build_pure(model)
        self.input_specs = input_specs if input_specs is not None else \
            specs
        if fixed_batch is not None:
            # a jit.save artifact is exported at ONE batch size — the
            # StableHLO program has concrete shapes — so the only legal
            # bucket is the exported batch: every micro-batch pads up
            # to it (export at batch = intended max_batch to serve).
            # Explicit conflicting buckets would compile fine here and
            # then die deep inside jax.export at first dispatch — catch
            # them typed at construction instead.
            fb = (int(fixed_batch),)
            asked = None
            if buckets is not None:
                asked = resolve_buckets(buckets, None)
            elif max_batch is not None and int(max_batch) != fb[0]:
                asked = (int(max_batch),)
            if asked is not None and asked != fb:
                raise InvalidArgumentError(
                    f"this artifact was exported at batch "
                    f"{fixed_batch} (concrete StableHLO shapes) — "
                    f"the only legal bucket is {fb}, got {asked}; "
                    "drop the buckets/max_batch override or "
                    "re-export at the batch you want to serve")
            self.buckets = fb
        else:
            self.buckets = resolve_buckets(buckets, max_batch)

        def counted(params, inputs):
            # runs only while TRACING (the standard trace-side-effect
            # counter): one increment per (bucket, inner-sig) compile
            bucket = int(np.shape(inputs[0])[0]) if inputs else 0
            with self._lock:
                self.compile_counts[bucket] = \
                    self.compile_counts.get(bucket, 0) + 1
            if self.metrics is not None:
                self.metrics.counter("compiles_total").inc()
                self.metrics.counter(f"compiles_bucket_{bucket}").inc()
            out = self._pure(params, inputs)
            if not isinstance(out, (list, tuple)):
                out = (out,)
            return tuple(out)

        self._jit = jax.jit(counted)
        # per-bucket executable cost (obs.costmodel, ISSUE 13):
        # computed lazily on the first instrumented dispatch of each
        # bucket (obs_metrics on), published as cost gauges
        self._cost_by_bucket: Dict[int, Any] = {}
        from ..obs import hbm as obs_hbm
        obs_hbm.register("params", self, lambda e: e._params,
                         name="InferenceEngine.params")

    # -- model → pure fn ----------------------------------------------------

    def _build_pure(self, model):
        """Resolve (pure_fn(params, inputs) -> outputs, params, specs,
        fixed_batch). ``fixed_batch`` is non-None for exported
        (StableHLO) artifacts, whose shapes are concrete."""
        from ..nn.layer_base import Layer
        from ..jit import TranslatedLayer

        specs = None
        fixed_batch = None
        # Predictor adapter: unwrap the loaded artifact; the sidecar
        # metadata supplies warmup specs and the exported batch size.
        # Lazy import (serving ← inference only here, inference →
        # serving only inside Predictor.serve) and isinstance, not a
        # class-name string — subclasses must route through the adapter
        from ..inference import Predictor
        if isinstance(model, Predictor) and hasattr(model, "_layer"):
            metas = getattr(model, "_input_meta", [])
            specs = [(tuple((m.get("shape") or [1, 1])[1:]),
                      m.get("dtype") or "float32")
                     for m in metas] or None
            shapes = [m.get("shape") for m in metas if m.get("shape")]
            if shapes:
                fixed_batch = int(shapes[0][0])
            model = model._layer
        if type(model).__name__ == "_QuantRunner":
            raise UnimplementedError(
                "serving a quantized Predictor is not supported yet — "
                "its dequant wrapper materializes inputs with "
                "np.asarray, which cannot trace. Serve the fp32 "
                "artifact (quantize at export instead).")

        if isinstance(model, TranslatedLayer):
            exported = model._exported
            params = {p.name: p.data for p in model.parameters()}

            def pure(p, inputs):
                return exported.call(p, *inputs)
            return pure, params, specs, fixed_batch

        if isinstance(model, Layer):
            model.eval()  # serving is eval mode: dropout off, BN stats
            params = model.functional_state()
            from ..autograd import engine as autograd_engine
            from ..core.generator import rng_scope
            from ..core.tensor import Tensor

            def pure(p, inputs):
                import jax
                with autograd_engine.no_grad(), \
                        rng_scope(jax.random.key(0)):
                    with model.load_functional_state(p):
                        out = model(*[Tensor(a, stop_gradient=True)
                                      for a in inputs])

                def unwrap(o):
                    if isinstance(o, (list, tuple)):
                        return type(o)(unwrap(x) for x in o)
                    return o.data if isinstance(o, Tensor) else o
                return unwrap(out)
            return pure, params, specs, None

        if callable(model):
            return (lambda p, inputs: model(*inputs)), {}, specs, None
        raise InvalidArgumentError(
            f"InferenceEngine needs a Layer, TranslatedLayer, Predictor "
            f"or callable, got {type(model).__name__}")

    # -- online-learning deltas (ISSUE 19) ----------------------------------

    def update_param_rows(self, name: str, ids, rows) -> None:
        """Overwrite rows of one 2-D param in place — the serving half
        of the embedding delta loop. The engine's params ride every
        dispatch as jit ARGUMENTS (not baked constants), and this
        preserves shape/dtype, so a delta is visible on the next
        dispatch with zero recompiles."""
        import jax.numpy as jnp
        with self._lock:
            cur = self._params.get(name)
            if cur is None:
                raise InvalidArgumentError(
                    f"param {name!r} not served by this engine (have "
                    f"{sorted(self._params)}) — the delta publisher "
                    "and the serving model disagree on the param name")
            ids = np.asarray(ids, np.int64).reshape(-1)
            vals = np.asarray(rows)
            if np.ndim(cur) != 2 or vals.ndim != 2 or \
                    vals.shape != (ids.shape[0], cur.shape[1]):
                raise InvalidArgumentError(
                    f"delta shape {vals.shape} does not fit param "
                    f"{name!r} of shape {np.shape(cur)} (need "
                    f"[{ids.shape[0]}, {np.shape(cur)[-1]}])")
            if ids.size and (int(ids.max()) >= cur.shape[0]
                             or int(ids.min()) < 0):
                raise InvalidArgumentError(
                    f"delta ids out of range for param {name!r} with "
                    f"{cur.shape[0]} rows")
            self._params[name] = cur.at[jnp.asarray(ids)].set(
                jnp.asarray(vals, dtype=cur.dtype))

    def param_rows(self, name: str, ids) -> np.ndarray:
        """Read rows of one 2-D served param — the parity probe for the
        delta loop: after a resync the caller compares these bytes
        against the trainer's table to prove the replica converged."""
        with self._lock:
            cur = self._params.get(name)
            if cur is None:
                raise InvalidArgumentError(
                    f"param {name!r} not served by this engine (have "
                    f"{sorted(self._params)})")
            ids = np.asarray(ids, np.int64).reshape(-1)
            if np.ndim(cur) != 2:
                raise InvalidArgumentError(
                    f"param {name!r} is not 2-D (shape {np.shape(cur)})")
            if ids.size and (int(ids.max()) >= cur.shape[0]
                             or int(ids.min()) < 0):
                raise InvalidArgumentError(
                    f"row ids out of range for param {name!r} with "
                    f"{np.shape(cur)[0]} rows")
            return np.asarray(cur)[ids]

    # -- bucketing ----------------------------------------------------------

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, rows: int) -> int:
        """Smallest bucket covering ``rows``."""
        if rows < 1:
            raise InvalidArgumentError(f"need >= 1 row, got {rows}")
        for b in self.buckets:
            if rows <= b:
                return b
        raise InvalidArgumentError(
            f"{rows} rows exceed the largest bucket {self.buckets[-1]} "
            f"(buckets {list(self.buckets)}) — raise serve_max_batch/"
            "serve_buckets or split the request")

    def _inner_sig(self, arrays) -> tuple:
        # on the per-request admission path: keep it allocation-light
        out = []
        for a in arrays:
            if not isinstance(a, np.ndarray):
                a = np.asarray(a)
            out.append((a.shape[1:], _dtype_str(a.dtype)))
        return tuple(out)

    def _guard_retrace(self, sig) -> None:
        if sig in self._seen_inner_sigs:
            return
        if self._jsan is not None:
            self._jsan.note_signatures(len(self._seen_inner_sigs) + 1,
                                       kind="inner signature")
        if self._seen_inner_sigs and not self._retrace_warned \
                and core_flags.flag("jit_retrace_warn"):
            self._retrace_warned = True
            import warnings
            warnings.warn(
                "InferenceEngine is retracing: a request arrived with a "
                f"new non-batch signature {sig} (seen "
                f"{len(self._seen_inner_sigs)} before). Batch-size "
                "variation is absorbed by the buckets, but every "
                "distinct inner shape/dtype costs a full XLA compile "
                "per bucket — pad sequence dims to fixed lengths (set "
                "FLAGS_jit_retrace_warn=0 to silence).")
        self._seen_inner_sigs.add(sig)

    # -- dispatch -----------------------------------------------------------

    def pad_to_bucket(self, arrays: Sequence[np.ndarray]
                      ) -> Tuple[List[np.ndarray], int, int]:
        """Zero-pad the batch axis up to the covering bucket; returns
        (padded, rows, bucket)."""
        rows = int(np.shape(arrays[0])[0])
        for a in arrays[1:]:
            if int(np.shape(a)[0]) != rows:
                raise InvalidArgumentError(
                    "all inputs of one request batch must share the "
                    f"batch dim; got {[np.shape(a) for a in arrays]}")
        bucket = self.bucket_for(rows)
        if bucket == rows:
            return list(arrays), rows, bucket
        padded = []
        for a in arrays:
            a = np.asarray(a)
            pad = np.zeros((bucket - rows,) + a.shape[1:], a.dtype)
            padded.append(np.concatenate([a, pad], axis=0))
        return padded, rows, bucket

    def bucket_cost(self, padded: Sequence[np.ndarray]):
        """FLOPs + bytes of one dispatch of the covering bucket's
        executable (:class:`~paddle1_tpu.obs.costmodel
        .ExecutableCost`), memoized per bucket — XLA cost analysis of
        a separate, UNCOUNTED lowering (lowering the counted jit would
        corrupt the one-compile-per-bucket accounting)."""
        import jax
        from ..obs import costmodel as obs_costmodel
        bucket = int(np.shape(padded[0])[0])
        c = self._cost_by_bucket.get(bucket)
        if c is None:
            arrays = tuple(np.asarray(a) for a in padded)
            fb = obs_costmodel.tree_size_cost(self._params,
                                              batch=arrays)
            c = obs_costmodel.analyze(
                lambda: jax.jit(
                    lambda p, i: self._pure(p, i)).lower(
                    self._params, arrays),
                fallback=fb)
            with self._lock:
                c = self._cost_by_bucket.setdefault(bucket, c)
        return c

    def _maybe_publish_cost(self, padded, bucket: int) -> None:
        """Bucket cost gauges, first instrumented dispatch only
        (``obs_metrics`` gates the one-time analysis trace — plain
        serving pays a dict lookup)."""
        from ..obs.registry import metrics_on
        if not metrics_on():
            return
        cost = self.bucket_cost(padded)
        self.metrics.gauge(f"cost_bucket_{bucket}_flops").set(
            cost.flops)
        self.metrics.gauge(f"cost_bucket_{bucket}_bytes").set(
            cost.bytes_accessed)
        self.metrics.gauge("cost_exact").set(1.0 if cost.exact else 0.0)

    def dispatch_padded(self, padded: Sequence[np.ndarray],
                        bucket: Optional[int] = None):
        """Run the bucket executable on already-padded inputs (the
        Batcher path, which pads itself to time the pad separately).
        Returns the device output tuple WITHOUT reading back — the
        caller decides when to pay the device→host fetch (the Batcher
        shares one readback across a whole micro-batch)."""
        if bucket is None:
            bucket = int(np.shape(padded[0])[0])
        self._guard_retrace(self._inner_sig(padded))
        with self._lock:
            self.dispatch_counts[bucket] = \
                self.dispatch_counts.get(bucket, 0) + 1
        if self.metrics is not None \
                and bucket not in self._cost_by_bucket:
            self._maybe_publish_cost(padded, bucket)
        return self._jit(self._params, tuple(padded))

    def dispatch(self, arrays: Sequence[np.ndarray]):
        """Pad + run. Returns (device outputs tuple, rows, bucket)."""
        padded, rows, bucket = self.pad_to_bucket(arrays)
        return self.dispatch_padded(padded, bucket), rows, bucket

    def infer(self, arrays: Sequence[np.ndarray]) -> List[np.ndarray]:
        """Synchronous single-shot convenience: dispatch + read back +
        slice the padding off. One device→host round trip per call —
        the cost the Batcher exists to amortize."""
        outs, rows, _ = self.dispatch(arrays)
        return [np.asarray(o)[:rows] for o in outs]

    # -- warmup / accounting ------------------------------------------------

    def warm_up(self, example: Optional[Sequence[np.ndarray]] = None
                ) -> int:
        """Pre-compile every bucket at startup (the anti-cold-start
        knob: first-request latency stops including XLA compiles).
        Needs ``input_specs`` or one ``example`` request to synthesize
        shapes from. Returns the number of buckets compiled."""
        if example is not None:
            specs = [(tuple(np.shape(a)[1:]),
                      str(np.asarray(a).dtype)) for a in example]
        elif self.input_specs:
            # normalize the dtype spelling through np.dtype so the
            # recorded signature matches _inner_sig's form even when the
            # spec was given as e.g. np.float32 (str() of a dtype CLASS
            # would record "<class ...>" and misfire the retrace warning
            # on the first real request)
            specs = [(tuple(s), _dtype_str(np.dtype(d)))
                     for s, d in self.input_specs]
        else:
            raise InvalidArgumentError(
                "warm_up needs input_specs=[(shape_without_batch, "
                "dtype), ...] or an example request")
        import jax
        done = 0
        for b in self.buckets:
            outs = self._jit(self._params, tuple(
                np.zeros((b,) + tuple(shape), np.dtype(dt))
                for shape, dt in specs))
            jax.block_until_ready(outs)
            done += 1
        self._seen_inner_sigs.add(tuple(specs))
        return done

    def cache_stats(self) -> Dict[str, int]:
        """hits/misses across all buckets (the ParallelEngine idiom)."""
        with self._lock:
            compiles = sum(self.compile_counts.values())
            dispatches = sum(self.dispatch_counts.values())
        return {"hits": dispatches - min(compiles, dispatches),
                "misses": compiles}
